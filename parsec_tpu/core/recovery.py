"""Recovery plane: lineage re-execution, partition re-mapping, rejoin.

PR 5 finished the failure lifecycle at CONTAINMENT: a dead rank is
detected (EOF / corruption / heartbeat silence), the taskpools touching
it fail with structured errors, and the service degrades permanently.
This module adds the second exit from every containment path — RECOVER:

  1. **Lineage re-execution.**  When ``declare_peer_dead`` fires, the
     surviving ranks reconstruct the dead rank's lost tiles instead of
     failing the pool.  Each survivor deterministically computes the
     same recovery decision (coordinator = lowest surviving rank, with
     an AGREEMENT ROUND below converging the dead-set view), rewinds
     the affected pool's termdet counters (``taskpool_reset``),
     restores what the replay needs — the registration-time snapshot,
     an incremental tile checkpoint
     (``utils/checkpoint.TileCheckpointStore``), or the collection's
     re-runnable source (``DataCollection.set_init``) for tiles whose
     only copy died with their rank — and re-inserts the re-execution
     sub-DAG on the survivors (``ParameterizedTaskpool.startup``
     re-enumeration with translated owner-computes, or the pool's
     ``recovery_replay`` for insert-driven DTD pools).

     **Minimal replay** (``recovery_minimal``, default on): every pool
     with the lineage plane armed keeps a RECORDED per-task lineage
     ring (``Taskpool._lineage``: task key, write-flow tile versions,
     read versions, remote activation dests — recorded at
     ``complete_execution`` off the release path), and the restart
     re-executes only the LOST SET: the whole adopted dead partition
     (its log died with it), the survivor's not-yet-completed tasks,
     and the recorded backward closure of everything that must re-feed
     the dead partition's replay — ``minimal_plan`` below computes it
     from the RECORDED (not re-derived) edges, with the replay cut
     always landing on a checkpointed, snapshotted, or live-intact
     version.  Skipped tasks' deliveries are synthesized from those
     materialized versions; cross-survivor re-feeds negotiate over the
     TAG_RECOVER control lane (a peer that cannot honor a need nacks
     and both sides fall back).  Replay-from-restore-point stays the
     fallback — taken whenever the lineage ring evicted the cut, the
     pool is insert-driven/dynamic, or a need was refused — counted in
     ``parsec_recovery_full_replays_total``.  The ≤2x-makespan
     acceptance bound is the bound of the FALLBACK policy; minimal
     replay's headline is the ``parsec_tasks_reexecuted_total`` delta.

  2. **Partition re-mapping.**  The dead rank's key range re-balances
     onto survivors through a rank-translation table installed PER
     COLLECTION (``DataCollection.set_rank_translation``): ``rank_of``
     stays the pure distribution function while ``owner_of`` — which
     task placement, activation routing, and local-tile materialization
     consult — routes around the hole.  Pools over untouched
     collections never observe a re-mapped owner, so silent
     misdirection of unaffected jobs is structurally impossible.

  3. **Elastic rejoin.**  A restarted rank comes back with a bumped
     incarnation epoch (``--mca comm_epoch`` / ``PARSEC_COMM_EPOCH``),
     re-dials the transports, and performs a TAG_REJOIN handshake: the
     survivors validate the epoch against the fence recorded at death
     (stale frames of the previous incarnation are dropped before they
     can touch the Safra balance — see RemoteDepEngine), clear the dead
     mark, hand back the current translation table, and the rank takes
     its partition back for every subsequently attached pool.  Clock
     sync re-establishes through the ordinary TAG_CLOCK probe rounds on
     the re-dialed connection.

Safra/termdet reconciliation: the remote-dep engine keeps per-peer send
and receive counters next to the global balance; a recovery subtracts
the dead rank's whole contribution in one critical section (the same
contract ``faultinject.on_frame_fault`` established for injected drops)
and fences later frames from the dead incarnation, so the token sees
exactly the in-flight traffic among survivors and termination converges
after re-insertion.

Everything here is OPT-IN (``recovery_enable``, default 0): disabled,
every path reproduces PR 5's containment behavior exactly.

Agreement round (TAG_RECOVER): before computing the translation table,
every survivor converges its dead-set view with the coordinator —
non-coordinators report their observed deaths and wait (bounded,
``recovery_agree_timeout_s``) for the coordinator's CONFIRMED excusal
broadcast; the coordinator coalesces reports for
``recovery_agree_window_s`` and broadcasts the union, and a receiver
learning of a death it has not detected yet declares it immediately.
Near-simultaneous multi-deaths therefore land every survivor on the
SAME dead set (and the same wholesale-recomputed table) instead of
transiently divergent ones; only a coordinator that dies mid-round
degrades to the old bounded behavior (the waiter times out and
proceeds with its local view — never silent, one
``recovery_max_attempts`` slot at worst).

Known limits (documented, structured-failure fallbacks): pools whose
collections lack both a snapshot and an ``init_fn`` for the adopted
tiles, cancelled pools, and a rank's own injected death are not
recovered.  DynamicTaskpool pools recover with a FULL replay (their
discovered DAG has no enumeration to filter) and re-arm their
distributed termination hold across the restart.  Rejoin is supported
on all three transports — the shm survivor re-creates its unlinked
inbound rings when the death is declared, so a restarted incarnation's
TAG_REJOIN handshake finds fresh rings (comm/shm.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from parsec_tpu.core.taskpool import (ParameterizedTaskpool, Taskpool,
                                      TaskpoolState)
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose, warning

params.register("recovery_max_attempts", 2,
                "per-taskpool budget of peer-death recoveries: one more "
                "rank dying after this many restarts fails the pool "
                "with the contained structured error instead of "
                "recovering again (survivor exhaustion stays a CLEAN "
                "failure, never a loop)")
params.register("recovery_snapshot", 1,
                "snapshot each registered pool's local collection tiles "
                "at attach — the lineage restore point for the pool's "
                "own partition (a dead rank's ADOPTED tiles restore "
                "from the collection's init_fn re-runnable source).  "
                "0 relies on init_fn alone")
params.register("recovery_drain_s", 10.0,
                "bound on waiting for in-flight stale-generation task "
                "bodies to leave the workers before tiles are restored "
                "(the run_epoch fence discards them at completion; this "
                "wait keeps their in-place writes off restored data)")
params.register("recovery_rejoin", 1,
                "accept TAG_REJOIN handshakes from restarted "
                "incarnations of dead ranks (needs recovery_enable; "
                "0 keeps the PR 3 zombie-reconnect rejection)")
params.register("recovery_completed_grace_s", 30.0,
                "how long a LOCALLY-completed pool stays restartable "
                "after its termination: within the window a peer death "
                "still restarts it (another survivor may need its "
                "re-executed partition — local completion is not "
                "global), past it the pool's recovery spec and tile "
                "snapshots are evicted, so a resident service's job "
                "history is never resurrected or leaked")
params.register("recovery_lineage", 1,
                "record the per-task lineage ring (task key, write-flow "
                "tile versions, read versions, remote dests) at "
                "complete_execution for every registered pool — the "
                "recorded edges minimal replay walks.  0 disables "
                "recording AND minimal replay (needs recovery_enable)")
params.register("recovery_lineage_ring", 8192,
                "per-pool bound on lineage records and completed-key "
                "tracking; a pool whose completions exceed it falls "
                "back to replay-from-restore-point on the next death "
                "(counted in parsec_recovery_full_replays_total)")
params.register("recovery_minimal", 1,
                "re-execute only the recorded-lineage minimal set on a "
                "peer death (adopted partition + pending tasks + the "
                "backward closure re-feeding them) instead of the "
                "whole local partition.  Falls back to the full "
                "restore-point replay whenever the plan is infeasible "
                "(ring evicted, no snapshot for an exact-version cut, "
                "a peer nacked a re-feed need, dynamic/insert-driven "
                "pool)")
params.register("recovery_agree_window_s", 0.25,
                "coordinator-side coalescing window of the dead-set "
                "agreement round: death reports arriving within it "
                "merge into ONE confirmed excusal broadcast, so "
                "near-simultaneous multi-deaths cannot transiently "
                "diverge survivors' translation tables")
params.register("recovery_agree_timeout_s", 3.0,
                "how long a non-coordinator survivor waits for the "
                "confirmed dead-set broadcast (and a minimal-replay "
                "requester for its need acks, and a DTD skip-agreement "
                "participant for the frontier/prefix round) before "
                "proceeding with its local view / full replay — the "
                "bounded fallback when the coordinator itself died "
                "mid-round")
params.register("recovery_need_rounds", 2,
                "bound on minimal-replay need-negotiation rounds per "
                "pool restart: a merged seed closure that WIDENS the "
                "remote needs re-issues a second need->ack/nack round "
                "against the peers' frozen plans (acked when the "
                "resolved producers are already in the frozen replay "
                "set) instead of falling straight back to full replay; "
                "past the cap the fallback is taken, counted in "
                "parsec_recovery_need_rounds_total{outcome=exhausted}")
params.register("recovery_dtd_skip", 1,
                "cross-rank skip agreement for multi-rank DTD pools "
                "(needs recovery_enable + recovery_minimal): survivors "
                "agree on the largest common skippable insert-stream "
                "prefix and the replay ghost-tracks it instead of "
                "re-executing; 0 keeps the always-full DTD replay.  "
                "Round timeouts ride recovery_agree_timeout_s")


class RecoveryUnsupported(RuntimeError):
    """A pool or collection cannot be recovered (no snapshot, no
    re-runnable source, unsupported pool type); the peer death then
    takes the containment path with this as context."""


# ---------------------------------------------------------------------------
# lineage planning (pure; unit-tested on hand-built DAGs)
# ---------------------------------------------------------------------------

class LineageRecord:
    """One completed task in a lineage log: the tile versions it read
    and the tile versions it produced (versions are per-tile monotone,
    the datum version-clock discipline).  ``rmap``/``wmap`` key the
    same pairs by FLOW NAME (minimal replay synthesizes per-flow
    deliveries from them); ``dests`` are the remote ranks this task's
    activations reached (the minimal-plan seeds); ``seq`` is the
    recording order — for DTD pools the insert-stream position rides
    in the key's tid, so the record doubles as insert-stream lineage."""

    __slots__ = ("key", "reads", "writes", "dests", "rmap", "wmap",
                 "seq")

    def __init__(self, key: Any,
                 reads: List[Tuple[Any, int]] = (),
                 writes: List[Tuple[Any, int]] = (),
                 dests=(), rmap: Optional[Dict] = None,
                 wmap: Optional[Dict] = None, seq: int = -1):
        self.key = key
        self.reads = list(reads)
        self.writes = list(writes)
        self.dests = frozenset(dests)
        self.rmap = dict(rmap or {})
        self.wmap = dict(wmap or {})
        self.seq = seq


class LineageLog:
    """Ring-bounded per-pool lineage (``Taskpool._lineage``): appended
    by worker threads at ``complete_execution`` (deque append + set add
    under the GIL — no lock round-trips beyond what termdet already
    takes), read by the recovery thread AFTER the run_epoch fence
    drained every in-flight body.  ``overflow`` latches once the ring
    or the completed-key set exceeds its cap: the recorded view is no
    longer complete, so the next restart takes the full-replay
    fallback instead of planning from a truncated log."""

    __slots__ = ("cap", "records", "completed", "overflow", "_sends",
                 "ckpt")

    def __init__(self, cap: int, ckpt=None):
        self.cap = max(16, int(cap))
        self.records: deque = deque(maxlen=self.cap)
        self.completed: set = set()
        self.overflow = False
        #: id(task) -> remote dests noted by flush_activations while
        #: the task's release path runs (same worker thread records)
        self._sends: Dict[int, set] = {}
        #: incremental checkpoint store (utils/checkpoint.py), shared
        #: across the context's pools; None = capture plane off
        self.ckpt = ckpt

    def note_send(self, task, ranks) -> None:
        s = self._sends.get(id(task))
        if s is None:
            self._sends[id(task)] = s = set()
        s.update(ranks)

    def snap_reads(self, task) -> Dict[str, Tuple[Any, int]]:
        """Per-flow (tile, version) of every collection-backed input —
        taken BEFORE complete_write bumps the clocks, so an RW flow
        records the version the body actually consumed."""
        rmap: Dict[str, Tuple[Any, int]] = {}
        for flow in task.task_class._in_flows:
            copy = task.data.get(flow.name)
            if copy is None:
                continue
            d = copy.data
            if d is not None and d.collection is not None:
                rmap[flow.name] = (d.key, copy.version)
        return rmap

    def record(self, task, rmap) -> None:
        wmap: Dict[str, Tuple[Any, int]] = {}
        for flow in task.task_class._write_flows:
            copy = task.data.get(flow.name)
            if copy is None or copy.data is None:
                continue
            d = copy.data
            if d.collection is None:
                continue   # arena/NEW temporaries are not tile lineage
            ver = d.newest_version()
            wmap[flow.name] = (d.key, ver)
            ckpt = self.ckpt
            if ckpt is not None:
                host = d.copy_on(0)
                if host is not None and host.payload is not None \
                        and host.version == ver:
                    # captures key by (collection identity, tile): a
                    # later job's same-NAMED collection must never be
                    # served this job's bytes as a replay cut
                    ckpt.note_write((id(d.collection), d.key), ver,
                                    host.payload)
        dests = self._sends.pop(id(task), None)
        if len(self.completed) >= self.cap or \
                len(self.records) >= self.cap:
            self.overflow = True   # a truncated log cannot plan
            return
        self.completed.add(task.key)
        self.records.append(LineageRecord(
            task.key, reads=list((rmap or {}).values()),
            writes=list(wmap.values()), dests=dests or (),
            rmap=rmap, wmap=wmap, seq=task.seq))
        if len(self.records) < len(self.completed):
            # two workers raced the cap guard and the bounded deque
            # silently evicted a record: the log is incomplete — latch,
            # or the planner would trust a truncated view
            self.overflow = True

    def clear(self) -> None:
        self.records.clear()
        self.completed.clear()
        self._sends.clear()
        self.overflow = False


def lineage_plan(log: List[LineageRecord],
                 surviving: Dict[Any, int],
                 needed: Dict[Any, int]):
    """The minimal re-execution set: walk backward from the ``needed``
    (tile -> version) outputs to the last surviving version of every
    input.

    ``surviving`` maps tile -> highest version still materialized on a
    live rank (registration snapshots are version 0 of every tile).  A
    needed (tile, version) with ``surviving[tile] >= version`` costs
    nothing; otherwise its producer joins the plan and that producer's
    reads become needed.  Returns ``(tasks, base)``: the re-execution
    set in log (= valid topological) order, and the {tile: version}
    frontier the restore must materialize before replay starts.
    """
    producer: Dict[Tuple[Any, int], int] = {}
    for i, rec in enumerate(log):
        for tile, ver in rec.writes:
            producer[(tile, ver)] = i
    chosen: set = set()
    base: Dict[Any, int] = {}
    work = deque((t, v) for t, v in needed.items())
    seen: set = set()
    while work:
        tile, ver = work.popleft()
        if (tile, ver) in seen:
            continue
        seen.add((tile, ver))
        if surviving.get(tile, -1) >= ver:
            base[tile] = max(base.get(tile, -1), min(ver,
                                                     surviving[tile]))
            continue
        idx = producer.get((tile, ver))
        if idx is None:
            raise RecoveryUnsupported(
                f"lineage broken: no producer and no surviving copy of "
                f"{tile!r} v{ver}")
        if idx in chosen:
            continue
        chosen.add(idx)
        for r in log[idx].reads:
            work.append(r)
    return [log[i].key for i in sorted(chosen)], base


class ReplayPlan:
    """Output of :func:`minimal_plan`: the re-execution set, the tile
    versions the restore must rewind to, the deliveries to synthesize
    for edges whose producer is skipped, and the cross-survivor
    re-feed needs to negotiate."""

    __slots__ = ("tasks", "base", "synth", "needs")

    def __init__(self):
        self.tasks: set = set()
        #: tile -> version the restore rewinds it to (desc-read cuts)
        self.base: Dict[Any, int] = {}
        #: (consumer_key, consumer_flow, tile|None, version, producer_key)
        self.synth: List[Tuple] = []
        #: (peer_rank, consumer_key, consumer_flow)
        self.needs: List[Tuple[int, Any, str]] = []


def minimal_plan(records, *, dead_set, pending=(), adopted=(),
                 live=None, materializable=None, edges=None,
                 extra_seeds=()) -> ReplayPlan:
    """The recorded-lineage minimal re-execution set for ONE rank.

    Starts from the lost work — the whole adopted dead partition (its
    log died with it), the local not-yet-completed tasks, and every
    recorded task whose activations reached a dead rank (``dests`` —
    the dead partition's replay must be re-fed) — then walks the
    RECORDED edges backward: a task-fed input whose (tile, version) is
    still materializable (live-intact, checkpointed, or snapshotted —
    the replay cut) becomes a synthesized delivery; otherwise its
    recorded producer joins the set.  Soundness against in-place tile
    mutation: a re-run writer that would regress a tile below its live
    version pulls every recorded LATER writer in (their re-executed
    writebacks reproduce the final state), and a collection-direct
    (desc) read rewinds its tile to the pool-attach snapshot version.
    Cross-survivor edges become ``needs`` the caller negotiates.

    ``edges(key)`` yields the structural task-fed/desc input edges of
    one task (the coordinator derives them from the task classes; unit
    tests pass a dict lookup):

    * ``("task", producer_key, producer_flow, consumer_flow, where,
      is_ctl)`` with ``where`` in ``"local"`` / ``"dead"`` /
      ``("peer", rank)``
    * ``("desc", tile, snapshot_version)``

    Raises :class:`RecoveryUnsupported` when the recorded view cannot
    prove the plan sound (evicted producer record, unrecorded later
    writer, no exact-version cut) — the caller then takes the full
    restore-point replay.
    """
    live = dict(live or {})
    mat = {t: set(v) for t, v in (materializable or {}).items()}
    by_key = {r.key: r for r in records}
    writers: Dict[Any, List[Tuple[int, Any]]] = {}
    for r in records:
        for t, v in r.writes:
            writers.setdefault(t, []).append((v, r.key))
    for lst in writers.values():
        lst.sort(key=lambda p: p[0])

    plan = ReplayPlan()
    work: deque = deque()

    def join(key):
        if key not in plan.tasks:
            plan.tasks.add(key)
            work.append(key)

    for k in pending:
        join(k)
    for k in adopted:
        join(k)
    for k in extra_seeds:
        join(k)
    for r in records:
        if r.dests & dead_set:
            join(r.key)

    def usable(tile, ver) -> bool:
        return ver == live.get(tile) or ver in mat.get(tile, ())

    def join_later_writers(tile, after: int) -> None:
        """The tile's content will regress below its live version:
        every recorded later writer re-runs so the re-executed
        writeback chain reproduces the final state."""
        lst = writers.get(tile, ())
        lv = live.get(tile)
        if lv is not None and lv > after:
            covered = max((v for v, _k in lst), default=-1)
            if covered < lv:
                raise RecoveryUnsupported(
                    f"minimal replay: the writer of {tile!r} v{lv} is "
                    "not in the recorded lineage")
        for v, k in lst:
            if v > after:
                join(k)

    synth_seen: set = set()
    while work:
        key = work.popleft()
        rec = by_key.get(key)
        if rec is not None:
            for tile, ver in rec.writes:
                if live.get(tile, ver) > ver:
                    join_later_writers(tile, ver)
        if edges is None:
            continue
        for edge in edges(key):
            if edge[0] == "desc":
                _kind, tile, snap_ver = edge
                lv = live.get(tile)
                if lv is None:
                    continue   # tile not materialized here (external)
                if snap_ver is None:
                    raise RecoveryUnsupported(
                        f"minimal replay: no snapshot version for a "
                        f"desc read of {tile!r} (recovery_snapshot=0?)")
                if lv != snap_ver:
                    if snap_ver not in mat.get(tile, ()):
                        raise RecoveryUnsupported(
                            f"minimal replay: desc read of {tile!r} "
                            f"needs v{snap_ver}, which is not "
                            "materializable")
                    prev = plan.base.get(tile)
                    if prev is None or snap_ver < prev:
                        plan.base[tile] = snap_ver
                    join_later_writers(tile, snap_ver)
                continue
            _kind, pkey, pflow, cflow, where, ctl = edge
            if where == "dead":
                continue   # re-fed by the dead partition's replay
            if isinstance(where, tuple):
                plan.needs.append((where[1], key, cflow))
                continue
            if pkey in plan.tasks:
                continue   # natural re-delivery
            prec = by_key.get(pkey)
            if prec is None:
                # no record and not pending/planned: the ring evicted
                # the producer — the recorded view is incomplete
                raise RecoveryUnsupported(
                    f"minimal replay: producer {pkey!r} of {key!r} has "
                    "no lineage record (ring evicted?)")
            sk = (key, cflow, pkey)
            if ctl:
                if sk not in synth_seen:
                    synth_seen.add(sk)
                    plan.synth.append((key, cflow, None, 0, pkey))
                continue
            crec = by_key.get(key)
            tv = crec.rmap.get(cflow) if crec is not None else None
            if tv is None:
                tv = prec.wmap.get(pflow)
            if tv is not None and usable(*tv):
                if sk not in synth_seen:
                    synth_seen.add(sk)
                    plan.synth.append((key, cflow, tv[0], tv[1], pkey))
                continue
            join(pkey)

    # a producer that joined AFTER one of its edges chose synthesis
    # now re-delivers naturally: drop the synth twin or the consumer's
    # arrival count overshoots
    plan.synth = [s for s in plan.synth if s[4] not in plan.tasks]
    return plan


def dtd_skip_prefix(frontiers: Dict[int, int],
                    landed: Dict[int, Dict[Any, int]],
                    writes) -> Tuple[int, Dict[Any, int], Dict[Any, int]]:
    """The largest common skippable DTD insert-stream prefix (pure;
    unit-tested on hand-built ladders).

    ``frontiers[rank]`` is each survivor's completion frontier (every
    LOCAL insert position below it completed); ``landed[rank][wire]``
    the whole-covering version whose bytes that rank's datum holds;
    ``writes`` the SPMD-identical ``(pos, wire)`` write ladder (the
    coordinator uses its own — the streams are identical by the DTD
    contract).

    A prefix ``K`` is honorable when, for every tile, the version the
    skipped prefix leaves it at (``vcut`` = number of writes below K)
    is HELD by some survivor (``landed == vcut``) — that rank becomes
    the tile's designated holder, serving the cut value in place of
    the skipped producers' deliveries.  Tiles the prefix never writes
    (``vcut == 0``) restore from the pool-attach snapshot / init_fn
    instead.  Returns ``(K, holders, vcut)``; ``K == 0`` means no
    common prefix is consistent with the survivors' materializable
    cuts and the gang takes the full replay."""
    from bisect import bisect_left
    if not frontiers:
        return 0, {}, {}
    top = min(frontiers.values())
    if top <= 0:
        return 0, {}, {}
    by_tile: Dict[Any, List[int]] = {}
    for pos, wire in writes:
        by_tile.setdefault(wire, []).append(pos)
    for lst in by_tile.values():
        lst.sort()
    ranks = sorted(landed)
    # feasibility only changes where a write enters/leaves the prefix,
    # so only the CLASS MAXIMA need testing: the frontier itself plus
    # each write position below it (any feasible K shares its class
    # maximum's vcuts, so the largest feasible K is always one of
    # these).  Bounds the scan by the write-ladder size (itself capped
    # by the lineage ring) instead of the raw insert count.
    cands = [top] + sorted({p for p, _w in writes if 0 < p < top},
                           reverse=True)
    for k in cands:
        holders: Dict[Any, int] = {}
        vcuts: Dict[Any, int] = {}
        ok = True
        for wire, poss in by_tile.items():
            vcut = bisect_left(poss, k)   # writes at positions < k
            if vcut == 0:
                continue
            holder = next((r for r in ranks
                           if landed[r].get(wire, 0) == vcut), None)
            if holder is None:
                ok = False
                break
            holders[wire] = holder
            vcuts[wire] = vcut
        if ok:
            return k, holders, vcuts
    return 0, {}, {}


# ---------------------------------------------------------------------------
# the coordinator
# ---------------------------------------------------------------------------

class RecoveryCoordinator:
    """Per-context recovery driver (``Context.recovery``).

    Containment hands it peer deaths on the comm thread
    (``on_peer_dead``); the actual restart work runs on a dedicated
    recovery thread so the transport loop keeps beating hearts while
    tiles restore.  All mutable state is guarded by ``_lock``; the
    restart pipeline itself is serialized by the single worker thread.
    """

    def __init__(self, context):
        self.context = context
        self.enabled = True
        self.max_attempts = int(params.get("recovery_max_attempts", 2))
        self.snapshot_on = bool(int(params.get("recovery_snapshot", 1)))
        self.drain_s = float(params.get("recovery_drain_s", 10.0))
        self.completed_grace = float(
            params.get("recovery_completed_grace_s", 30.0))
        self.lineage_on = bool(int(params.get("recovery_lineage", 1)))
        self.lineage_cap = int(params.get("recovery_lineage_ring", 8192))
        self.minimal_on = bool(int(params.get("recovery_minimal", 1)))
        self.agree_window = float(
            params.get("recovery_agree_window_s", 0.25))
        self.agree_timeout = float(
            params.get("recovery_agree_timeout_s", 3.0))
        self.need_rounds_cap = int(params.get("recovery_need_rounds", 2))
        self.dtd_skip_on = bool(int(params.get("recovery_dtd_skip", 1)))
        #: incremental tile checkpoint store (utils/checkpoint.py),
        #: shared by every registered pool's lineage hook; None = the
        #: capture plane is off (interval 0, the default)
        self.ckpt = None
        ck_interval = float(
            params.get("recovery_checkpoint_interval_s", 0.0))
        if ck_interval > 0:
            from parsec_tpu.utils.checkpoint import TileCheckpointStore
            self.ckpt = TileCheckpointStore(
                ck_interval,
                int(params.get("recovery_checkpoint_keep", 2)))
        self._lock = threading.Lock()
        #: TAG_RECOVER control-lane state: dead-set agreement reports/
        #: confirmations and minimal-replay need bookkeeping
        #: (guarded-by: _ctl_cond)
        self._ctl_cond = threading.Condition()
        self._agree_reports: Dict[int, set] = {}
        self._agree_confirmed: set = set()
        #: taskpool_id -> "open" | "frozen" | "full" (minimal-replay
        #: plan lifecycle; a need arriving on a frozen plan nacks)
        self._plan_state: Dict[int, str] = {}
        #: taskpool_id -> producer keys peers asked this rank to
        #: include in its replay set
        self._extra_seeds: Dict[int, set] = {}
        #: (taskpool_id, peer) -> ack verdict of our need request
        self._need_acks: Dict[Tuple[int, int], bool] = {}
        #: (taskpool_id, peer) -> (round, mode) — the mode-agreement
        #: votes, stamped with the voter's restart-attempt round so a
        #: stale round's ballot can never satisfy (or poison) the
        #: current agreement
        self._peer_modes: Dict[Tuple[int, int], Tuple[int, str]] = {}
        #: taskpool_id -> (round, mode) this rank itself declared —
        #: replayed to late voters so an early committer's exit from
        #: the agreement wait cannot strand them into a timeout
        self._my_mode: Dict[int, Tuple[int, str]] = {}
        #: (taskpool_id, rank) -> (round, report) — DTD skip-agreement
        #: frontier/landed reports collected by the coordinator
        #: (guarded-by: _ctl_cond)
        self._skip_reports: Dict[Tuple[int, int], Tuple[int, dict]] = {}
        #: taskpool_id -> (round, skipset msg) — the coordinator's
        #: agreed-prefix broadcast (guarded-by: _ctl_cond)
        self._skip_set: Dict[int, Tuple[int, dict]] = {}
        #: taskpool_id -> ranks that reported LOCAL completion — the
        #: retirement handshake's quorum; when every live rank is in,
        #: the coordinator broadcasts the retirement and the pool
        #: leaves restartable state (guarded-by: _ctl_cond)
        self._retire_reports: Dict[int, set] = {}
        #: taskpool_id -> this rank's FROZEN minimal replay set — a
        #: second-round need arriving against a frozen plan acks iff
        #: its resolved producers are already IN the set (no plan
        #: change needed), instead of the unconditional r12 nack
        #: (guarded-by: _ctl_cond)
        self._frozen_tasks: Dict[int, set] = {}
        self._rde = None               # RemoteDepEngine (attach_comm)
        #: taskpool_id -> {"tp", "collections", "replay"}
        #: (guarded-by: _lock)
        self._specs: Dict[int, Dict[str, Any]] = {}
        #: collection snapshots: id(dc) -> {idx: ndarray}
        #: (guarded-by: _lock)
        self._snaps: Dict[int, Dict[Tuple, np.ndarray]] = {}
        self._snap_dcs: Dict[int, Any] = {}      # keep dc alive w/ snap
        self._attempts: Dict[int, int] = {}      # guarded-by: _lock
        self._active: set = set()                # guarded-by: _lock
        self._events: deque = deque()            # guarded-by: _lock
        self._worker: Optional[threading.Thread] = None  # guarded-by: _lock
        #: dead rank -> adopting survivor, cluster-wide view
        #: (guarded-by: _lock)
        self._dead_map: Dict[int, int] = {}
        #: deaths accepted but not yet processed by the recovery thread
        #: — excused() must cover them, or the window between
        #: on_peer_dead and _process_event routes secondary send
        #: failures into containment and fails the very pool being
        #: rebuilt (guarded-by: _lock)
        self._pending_dead: set = set()
        self._translated: List[Any] = []         # guarded-by: _lock
        #: rejoined incarnation epochs (guarded-by: _lock)
        self._peer_epochs: Dict[int, int] = {}
        #: rejoins that landed while a restart was active: their
        #: translation entries clear once the restart pipeline drains
        #: (guarded-by: _lock)
        self._pending_untranslate: set = set()
        self._services: List[Any] = []           # guarded-by: _lock
        # observability (metrics plane reads these at scrape; the
        # counters move only on the recovery/comm threads)
        self.counts = {"started": 0, "completed": 0, "failed": 0}
        self.tasks_reexecuted = 0
        self.rejoins = 0
        #: restart-policy split: minimal (recorded-lineage plan OR an
        #: agreed DTD skip prefix) vs full (replay-from-restore-point
        #: fallback) pool restarts
        self.minimal_replays = 0
        self.full_replays = 0
        #: concluded DTD skip agreements (a nonzero prefix agreed AND
        #: committed through the mode round) — the counter the
        #: kill-dtd-minimal chaos case proves against
        self.skip_agreements = 0
        #: completed pools retired through the explicit handshake
        #: (coordinator confirmed every live rank locally complete)
        self.retirements = 0
        #: pools whose retirement handshake never concluded and whose
        #: restartable state fell back to the grace-window eviction
        #: (coordinator died mid-handshake, lost report) — the PR 14
        #: residual, previously silent; journaled as retire_degraded
        self.retire_degraded = 0
        #: pools whose "retired" journal event already emitted (the
        #: auditor's exactly-one-retirement-outcome invariant; the
        #: handshake can apply twice when Context.wait's quiescence
        #: retire races the coordinator broadcast) guarded-by: _lock
        self._retired_emitted: set = set()
        #: need-negotiation rounds by outcome (acked / nacked /
        #: widened / exhausted) — a silent round is a failed gate
        self.need_round_counts = {"acked": 0, "nacked": 0,
                                  "widened": 0, "exhausted": 0}
        from parsec_tpu.prof.metrics import Histogram
        self.duration_hist = Histogram()
        m = getattr(context, "metrics", None)
        if m is not None:
            m.register_collector(self._collect)

    # -- wiring ----------------------------------------------------------
    def attach_comm(self, rde) -> None:
        """Called by RemoteDepEngine at construction: wire the rejoin
        handshake and let the transport accept reconnections from dead
        ranks (the recovery knob gates it)."""
        self._rde = rde
        rde.ce.on_recover = self._on_recover_msg
        if int(params.get("recovery_rejoin", 1)):
            rde.ce.rejoin_allowed = True
            rde.ce.on_rejoin = self.on_rejoin_request

    def attach_service(self, service) -> None:
        with self._lock:
            self._services.append(service)

    def detach_service(self, service) -> None:
        with self._lock:
            if service in self._services:
                self._services.remove(service)

    def _notify_services(self, event: str, rank: int) -> None:
        with self._lock:
            services = list(self._services)
        for svc in services:
            try:
                svc.note_recovery(event, rank)
            except Exception as exc:
                debug_verbose(2, "recovery service notify: %s", exc)

    # -- registration ----------------------------------------------------
    def register_pool(self, tp: Taskpool) -> None:
        """Record a pool's recovery spec at attach and snapshot its
        collections' local tiles — the lineage restore point.  A pool
        without collections stays on the containment path."""
        collections = list(getattr(tp, "recovery_collections", ()) or ())
        spec = {"tp": tp, "collections": collections,
                "replay": getattr(tp, "recovery_replay", None),
                "completed_at": None}
        if collections:
            tp.on_complete(self._pool_done)
            if self.lineage_on:
                # arm the recorded lineage ring (the minimal-replay
                # evidence; complete_execution's hook is a None check
                # for every unregistered pool)
                tp._lineage = LineageLog(self.lineage_cap,
                                         ckpt=self.ckpt)
        snaps = []
        if collections and self.snapshot_on:
            for dc in collections:
                if not hasattr(dc, "local_tiles"):
                    continue
                #: idx -> (version at snapshot, payload copy) — the
                #: version stamp names this cut in the lineage planner
                snap: Dict[Tuple, Tuple[int, np.ndarray]] = {}
                try:
                    for idx in dc.local_tiles():
                        idx = tuple(idx) if isinstance(idx, (tuple, list)) \
                            else (idx,)
                        datum = dc.data_of(*idx)
                        copy = datum.pull_to_host()
                        if copy is not None and copy.payload is not None:
                            snap[idx] = (datum.newest_version(),
                                         np.array(copy.payload,
                                                  copy=True))
                except Exception as exc:
                    warning("recovery: snapshot of %s failed (%s); "
                            "relying on init_fn", dc.name, exc)
                    snap = {}
                snaps.append((dc, snap))
        with self._lock:
            self._specs[tp.taskpool_id] = spec
            for dc, snap in snaps:
                # latest registration wins: for sequential pools over
                # one collection the snapshot must reflect the state at
                # THIS pool's attach (its replay base), not the first's
                self._snaps[id(dc)] = snap
                self._snap_dcs[id(dc)] = dc
            self._sweep_locked()

    def _pool_done(self, tp) -> None:
        """Completion callback: stamp the grace-window clock (a restart
        re-stamps it on re-termination) and start the RETIREMENT
        HANDSHAKE — report this rank's local completion to the
        coordinator, which confirms global quiescence (every live rank
        locally complete) before the pool leaves restartable state.
        The ``recovery_completed_grace_s`` window remains the bounded
        FALLBACK (dead coordinator, lost report): past it the spec
        evicts exactly as before."""
        with self._lock:
            spec = self._specs.get(tp.taskpool_id)
            if spec is not None:
                spec["completed_at"] = time.monotonic()
        if spec is None:
            return
        self._report_retire(tp)

    def _report_retire(self, tp) -> None:
        """Send (or locally record) this rank's local-completion report
        for one pool; called from the completion callback (worker
        thread) — never holds _lock across the send."""
        rde = self._rde
        ce = rde.ce if rde is not None else None
        if ce is None or ce.nranks <= 1:
            # single-rank context: local completion IS global
            self._apply_retired(tp.taskpool_id)
            return
        coord = rde.recovery_coordinator()
        jr = self.context.journal
        if jr is not None:
            jr.emit("retire_report", pool=tp.taskpool_id, coord=coord)
        if coord == ce.rank:
            self._note_retire_report(tp.taskpool_id, ce.rank)
            return
        from parsec_tpu.comm.engine import TAG_RECOVER
        try:
            ce.send_am(TAG_RECOVER, coord,
                       {"k": "retire", "tp": tp.taskpool_id})
        except OSError:
            pass   # grace-window fallback bounds the miss

    def _note_retire_report(self, tpid: int, src: int) -> None:
        """Coordinator side: record one rank's local completion and,
        once EVERY live rank reported, broadcast the confirmed
        retirement.  Quorum membership is evaluated at report time —
        a rank dying mid-handshake shrinks the live set and its
        restart path clears the report state for replayed pools."""
        rde = self._rde
        ce = rde.ce if rde is not None else None
        if ce is None:
            return
        jr = self.context.journal
        if jr is not None:
            jr.emit("retire_recv", pool=tpid, src=src)
        with self._ctl_cond:
            reported = self._retire_reports.setdefault(tpid, set())
            reported.add(src)
            live = {r for r in range(ce.nranks)
                    if r not in ce.dead_peers}
            done = live <= reported
        if not done:
            return
        with self._lock:
            if tpid in self._active or self._events:
                return   # a restart owns this pool; quorum re-collects
        from parsec_tpu.comm.engine import TAG_RECOVER
        for r in sorted(live - {ce.rank}):
            try:
                ce.send_am(TAG_RECOVER, r, {"k": "retired", "tp": tpid})
            except OSError:
                pass
        self._apply_retired(tpid)

    def _apply_retired(self, tpid: int) -> None:
        """Confirmed-retirement landing (both sides of the handshake):
        the pool is GLOBALLY done — it leaves restartable state now
        instead of dangling through the grace window, and a later peer
        death can never resurrect it (or re-fire its completion into
        the job service)."""
        emit = False
        with self._lock:
            spec = self._specs.get(tpid)
            tp = spec["tp"] if spec is not None else None
            if tp is None or tpid in self._active or not tp.completed:
                return
            tp.retired = True
            self.retirements += 1
            if tpid not in self._retired_emitted:
                # exactly ONE retirement-outcome journal event per pool
                # per rank — the auditor's invariant; a second apply
                # (quiescence-retire racing the broadcast) is absorbed
                self._retired_emitted.add(tpid)
                emit = True
        if emit:
            jr = self.context.journal
            if jr is not None:
                jr.emit("retired", pool=tpid)
            # no synchronous sweep: the retired flag already ends
            # restartability (on_peer_dead skips retired pools), and
            # the spec/snapshot/capture eviction rides the normal
            # sweep cadence (next registration / grace) so late
            # readers of the capture plane are not cut off mid-read
        debug_verbose(2, "rank %d: pool %d RETIRED (global quiescence "
                      "confirmed)", self.context.rank, tpid)

    def _sweep_locked(self) -> None:   # holds-lock: _lock
        """Evict specs (and the tile snapshots only they referenced) of
        pools that retired, were cancelled, or completed past the grace
        window — a resident service must not accumulate O(jobs served)
        pool objects and snapshot bytes, nor resurrect ancient jobs on
        a peer death.  Caller holds _lock."""
        now = time.monotonic()
        evicted: List[int] = []
        evicted_dcs: set = set()
        for tpid in list(self._specs):
            spec = self._specs[tpid]
            tp = spec["tp"]
            done_at = spec["completed_at"]
            stale = (getattr(tp, "retired", False) or tp.cancelled
                     or (done_at is not None
                         and now - done_at > self.completed_grace))
            if stale and tpid not in self._active:
                del self._specs[tpid]
                self._attempts.pop(tpid, None)
                evicted.append(tpid)
                evicted_dcs.update(id(dc) for dc in spec["collections"])
                if done_at is not None and not tp.cancelled \
                        and not getattr(tp, "retired", False):
                    # the pool completed but its retirement handshake
                    # never concluded (coordinator died mid-handshake,
                    # lost report) and no quiescence round retired it:
                    # the PR 14 grace-window degradation, counted and
                    # journaled instead of silent
                    self.retire_degraded += 1
                    jr = self.context.journal
                    if jr is not None:
                        jr.emit("retire_degraded", pool=tpid)
        if evicted:
            # the TAG_RECOVER control state retires with the spec — a
            # resident service must not accumulate per-restart entries
            # (safe nesting: _ctl_cond is never held while taking _lock)
            self._retired_emitted.difference_update(evicted)
            with self._ctl_cond:
                for tpid in evicted:
                    self._plan_state.pop(tpid, None)
                    self._extra_seeds.pop(tpid, None)
                    self._skip_set.pop(tpid, None)
                    self._retire_reports.pop(tpid, None)
                    self._frozen_tasks.pop(tpid, None)
                    for kk in [kk for kk in self._need_acks
                               if kk[0] == tpid]:
                        del self._need_acks[kk]
                    for kk in [kk for kk in self._peer_modes
                               if kk[0] == tpid]:
                        del self._peer_modes[kk]
                    for kk in [kk for kk in self._skip_reports
                               if kk[0] == tpid]:
                        del self._skip_reports[kk]
        live_dcs = {id(dc) for spec in self._specs.values()
                    for dc in spec["collections"]}
        for key in [k for k in self._snaps if k not in live_dcs]:
            self._snaps.pop(key, None)
            self._snap_dcs.pop(key, None)
        if self.ckpt is not None:
            # the incremental captures retire WITH the spec — keyed on
            # the EVICTED specs' collections, not on _snaps, so the
            # recovery_snapshot=0 configuration still evicts: a
            # resident service must not accumulate captures, and a
            # gc-recycled collection identity must start clean
            for key in evicted_dcs - live_dcs:
                self.ckpt.drop_owner(key)

    # -- containment hand-off (comm thread; must not block) --------------
    def on_peer_dead(self, rank: int, exc: Exception,
                     pools: List[Taskpool]):
        """Decide, per pool, recovery vs containment.  Returns
        ``(handled, leftover)``: ``handled`` True when this death is
        excused (the service degrades-but-survives even with zero
        affected pools); ``leftover`` are pools recovery will NOT take
        — the caller contains them as before."""
        ce = self._rde.ce if self._rde is not None else None
        if not self.enabled or ce is None \
                or getattr(ce, "fault_killed", False) \
                or rank == self.context.rank:
            return False, pools
        take: List[Taskpool] = []
        leave: List[Taskpool] = []
        touching = {tp.taskpool_id for tp in pools}
        with self._lock:
            # the restart set is GANG-WIDE per pool, not per-traffic:
            # the re-executed DAG is global, so every survivor must
            # restart a pool whose collections span the dead rank even
            # if ITS partition never exchanged a frame with it — a
            # survivor left on the old generation would park the new
            # generation's activations forever.  Registered pools whose
            # collections cannot contain the dead rank are genuinely
            # unaffected and stay untouched.
            candidates = list(pools)
            for spec in self._specs.values():
                tp = spec["tp"]
                # completed-but-not-RETIRED pools are candidates too:
                # local completion is not global completion, and a
                # survivor whose partition drained early must still
                # restart so the adopter's re-executed activations have
                # somewhere to land (retired = a quiescence round
                # proved the whole gang done; never resurrected)
                if tp.taskpool_id in touching \
                        or getattr(tp, "retired", False) \
                        or tp.cancelled or not spec["collections"]:
                    continue
                if tp.completed:
                    # locally complete: restartable only within the
                    # grace window — past it the gang has long since
                    # quiesced and a resident service's history must
                    # never be resurrected
                    done_at = spec["completed_at"]
                    if done_at is None or \
                            time.monotonic() - done_at \
                            > self.completed_grace:
                        continue
                if any(getattr(dc, "nodes", 1) > rank
                       for dc in spec["collections"]):
                    candidates.append(tp)
            for tp in candidates:
                spec = self._specs.get(tp.taskpool_id)
                # insert-driven pools (anything that is not a
                # parameterized enumeration) NEED a replay callable: a
                # base startup() re-enumerates nothing, and a restart
                # would restore the tiles, re-execute zero tasks, and
                # "complete" with silently reverted data
                replayable = spec is not None and (
                    spec["replay"] is not None
                    or isinstance(tp, ParameterizedTaskpool))
                # DynamicTaskpool pools (incl. distributed ones holding
                # a _dyn_hold) recover too: startup() re-seeds the
                # discovery roots and _restart_pool re-arms the hold
                ok = (spec is not None and spec["collections"]
                      and replayable
                      and not tp.cancelled
                      and not getattr(tp, "retired", False)
                      and not getattr(tp, "_compound_member", False)
                      and hasattr(tp.termdet, "taskpool_reset")
                      and self._attempts.get(tp.taskpool_id, 0)
                      < self.max_attempts)
                if ok:
                    self._attempts[tp.taskpool_id] = \
                        self._attempts.get(tp.taskpool_id, 0) + 1
                    self._active.add(tp.taskpool_id)
                    take.append(tp)
                elif tp.taskpool_id in touching:
                    leave.append(tp)   # containment, exactly as before
            self._events.append((rank, exc, take))
            self._pending_dead.add(rank)
            worker = self._worker
            if worker is None or not worker.is_alive():
                worker = threading.Thread(target=self._run,
                                          name="parsec-recovery",
                                          daemon=True)
                self._worker = worker
                worker.start()
        with self._ctl_cond:
            # a PREVIOUS restart left this pool's plan state "frozen":
            # reset it the moment the death is accepted, or a faster
            # peer's re-feed needs for THIS event get spuriously
            # nacked against the stale state (silently degrading every
            # death after the first to full replay).  Seeds promised
            # in an earlier event whose restart took the full path
            # (never popped) must not leak into this event's plan
            # either — a full replay honored them by re-running
            # everything
            for tp_ in take:
                tpid_ = tp_.taskpool_id
                self._plan_state.pop(tpid_, None)
                self._extra_seeds.pop(tpid_, None)
                self._frozen_tasks.pop(tpid_, None)
                # the restarted pool's retirement quorum re-collects
                # from its re-completions (stale reports must not
                # retire a pool a survivor is still replaying).  Skip
                # reports/broadcasts are NOT purged: like the mode
                # ballots they carry the restart-attempt round and
                # _plan_dtd_skip matches rounds — purging would delete
                # a FASTER peer's current-round report (a hang-detected
                # death lets peers report before we even declare) and
                # force a spurious full-replay fallback
                self._retire_reports.pop(tpid_, None)
        # excuse SYNCHRONOUSLY, on the declaring thread: a survivor
        # polling wait_quiescence every 50 ms must never observe
        # dead-but-not-yet-excused in the window before the recovery
        # worker gets scheduled (the fatal check would fail a run the
        # recovery is about to save); _process_event's excusal is then
        # a harmless repeat
        ce.excuse_peer(rank)
        self.counts["started"] += 1
        jr = self.context.journal
        if jr is not None:
            jr.emit("recovery_start", peer=rank,
                    pools=[tp.taskpool_id for tp in take],
                    contained=[tp.taskpool_id for tp in leave])
        self.context.telemetry_incident(
            f"recovery-start rank={rank} pools="
            f"{[tp.taskpool_id for tp in take]}")
        warning("rank %d: RECOVERY engaged for dead rank %d (%d pool(s) "
                "re-executing, %d contained)", self.context.rank, rank,
                len(take), len(leave))
        self._notify_services("start", rank)
        return True, leave

    def busy(self) -> bool:
        """A death was accepted, an event is queued, or a restart is
        mid-flight.  Global-quiescence deciders (Safra ring idle
        predicates, the sole-survivor short-circuits) consult this:
        declaring the gang done over a pool a queued restart is about
        to rewind would hand Context.wait back to the application
        while the restore overwrites the very tiles it then reads —
        the completed-pool-grace race the chaos smoke caught."""
        with self._lock:
            return bool(self._events or self._active
                        or self._pending_dead)

    def recovering(self, tp) -> bool:
        """Is a recovery restart pending/active for this pool?  The
        containment paths consult it to swallow secondary errors of the
        torn generation (dead-child sends, parked pulls) instead of
        failing a pool that is already being rebuilt."""
        with self._lock:
            return tp is not None and tp.taskpool_id in self._active

    def excused(self, rank: int) -> bool:
        with self._lock:
            return rank in self._dead_map or rank in self._pending_dead

    # -- the recovery thread ---------------------------------------------
    def _apply_untranslate(self) -> None:
        """Clear translation entries of ranks that rejoined while a
        restart was active, once the restart pipeline drained — a
        deferred clear nobody applies would leave the rejoined rank's
        partition re-mapped forever."""
        with self._lock:
            if self._active or self._events \
                    or not self._pending_untranslate:
                return
            pend = set(self._pending_untranslate)
            self._pending_untranslate.clear()
            translated = list(self._translated)
        for dc in translated:
            table = dict(dc._recovery_translate or {})
            for r in pend:
                table.pop(r, None)
            dc.set_rank_translation(table)

    def _run(self) -> None:
        while True:
            with self._lock:
                if not self._events:
                    # retire INSIDE the lock: on_peer_dead appends and
                    # checks worker liveness under the same lock, so an
                    # event can never strand between our empty-check
                    # and exit
                    self._worker = None
                    break
                rank, exc, pools = self._events.popleft()
            try:
                self._process_event(rank, exc, pools)
            except Exception as run_exc:   # the thread must drain events
                warning("rank %d: recovery event for rank %d failed: %s",
                        self.context.rank, rank, run_exc)
                self.counts["failed"] += 1
                with self._lock:
                    self._pending_dead.discard(rank)
                for tp in pools:
                    with self._lock:
                        self._active.discard(tp.taskpool_id)
                    self.context.record_pool_error(tp, exc)
                self._notify_services("failed", rank)
        self._apply_untranslate()

    def _process_event(self, rank: int, exc: Exception,
                       pools: List[Taskpool]) -> None:
        ctx, rde = self.context, self._rde
        ce = rde.ce
        t0 = time.monotonic()
        # 1. excuse + fence + Safra reconcile: from here, barriers and
        # quiescence run over the survivors, stale frames of the dead
        # incarnation are dropped before crediting, and the message
        # balance reflects live traffic only
        ce.excuse_peer(rank)
        rde.recovery_reconcile(rank)
        # AGREEMENT ROUND (TAG_RECOVER): converge the dead-set view
        # with the coordinator before any table is computed, so
        # near-simultaneous multi-deaths land every survivor on the
        # same set instead of transiently divergent ones (the round is
        # bounded — a dead coordinator degrades to the local view)
        observed = (set(ce.dead_peers) | {rank}) - {ce.rank}
        agreed = self._agree_dead_set(observed)
        # the translation recomputes WHOLESALE from the dead SET (not
        # incrementally from event order): two survivors detecting two
        # near-simultaneous deaths in opposite order must still land on
        # the same table, and a chained adopter death (1->2, then 2
        # dies) must collapse onto a live rank
        dead_set = (set(ce.dead_peers) | observed | agreed) - {ce.rank}
        survivors = sorted(r for r in range(ce.nranks)
                           if r not in dead_set)
        if not survivors:
            raise RecoveryUnsupported("no survivors")
        with self._lock:
            self._dead_map = {d: survivors[d % len(survivors)]
                              for d in dead_set}
            target = self._dead_map[rank]
            self._pending_dead.discard(rank)
        debug_verbose(1, "rank %d: recovery re-maps rank %d -> %d "
                      "(survivors %s)", ctx.rank, rank, target, survivors)
        ok = True
        for tp in pools:
            try:
                n = self._restart_pool(tp, rank, target)
                self.tasks_reexecuted += n
                debug_verbose(1, "rank %d: pool %d re-executes %d "
                              "task(s)", ctx.rank, tp.taskpool_id, n)
            except Exception as restart_exc:
                ok = False
                warning("rank %d: recovery of pool %d failed (%s); "
                        "containing", ctx.rank, tp.taskpool_id,
                        restart_exc)
                with self._lock:
                    self._active.discard(tp.taskpool_id)
                ctx.record_pool_error(tp, exc)
        # COORDINATOR SUCCESSION for the retirement handshake: a rank
        # dying mid-handshake (the coordinator with collected reports,
        # or a member that never reported) would silently degrade
        # retirement to the grace window — re-run the round over the
        # shrunken live set so it completes without degradation
        self._succeed_retirements(rank)
        dt = time.monotonic() - t0
        self.duration_hist.observe(dt)
        self.counts["completed" if ok else "failed"] += 1
        jr = ctx.journal
        if jr is not None:
            jr.emit("recovery_done", peer=rank, ok=ok,
                    duration_s=round(dt, 4))
        self._notify_services("done" if ok else "failed", rank)
        warning("rank %d: recovery for dead rank %d %s in %.2fs",
                ctx.rank, rank, "completed" if ok else "FAILED", dt)

    def _succeed_retirements(self, dead: int) -> None:
        """Retirement-handshake succession after a death: every
        survivor re-reports its locally-complete, unretired,
        not-restarting pools.  When the dead rank was the handshake
        coordinator (every coordinator is ``min(live)``, so ``dead <
        new coordinator`` identifies exactly that case) the NEW
        coordinator re-collects quorum from scratch — the old one took
        the collected reports down with it.  When a non-coordinator
        member died before reporting, the re-reports force the
        coordinator to re-evaluate quorum over the SHRUNKEN live set
        (report-time evaluation alone would wait for a report that can
        never come).  Idempotent on the collector side: a re-added
        report is a set re-add."""
        rde = self._rde
        ce = rde.ce if rde is not None else None
        if ce is None or ce.nranks <= 1:
            return
        coord = rde.recovery_coordinator()
        succession = dead < coord      # the dead rank WAS coordinator
        with self._lock:
            pools = [spec["tp"] for tpid, spec in self._specs.items()
                     if spec["completed_at"] is not None
                     and not getattr(spec["tp"], "retired", False)
                     and not spec["tp"].cancelled
                     and tpid not in self._active]
        jr = self.context.journal
        for tp in pools:
            if succession and jr is not None:
                jr.emit("retire_succession", pool=tp.taskpool_id,
                        coord=coord, dead=dead)
            self._report_retire(tp)

    def _restart_pool(self, tp: Taskpool, dead: int, target: int) -> int:
        """Rewind + restore + re-execute one pool.  Returns the local
        re-execution task count."""
        from parsec_tpu.core import scheduling
        ctx, rde = self.context, self._rde
        with self._lock:
            spec = self._specs[tp.taskpool_id]
        if getattr(tp, "retired", False):
            # globally done (a quiescence round proved the whole gang
            # finished): nothing left to re-execute anywhere
            with self._lock:
                self._active.discard(tp.taskpool_id)
            return 0
        # partition re-mapping on THIS pool's collections (plus the
        # pool-level table DTD integer affinities consult).  The
        # pre-restore window is TRANSACTIONAL: a failed pre-flight must
        # roll the tables back, or owner_of would keep routing the dead
        # partition here with no restored payloads — a later pool over
        # the same collection would then materialize zero-filled
        # adopted tiles and silently compute garbage
        with self._lock:
            dead_map = dict(self._dead_map)
        prev_tables = [(dc, dict(dc._recovery_translate)
                        if dc._recovery_translate else None)
                       for dc in spec["collections"]]
        for dc in spec["collections"]:
            # the FULL normalized map, not just this event's entry: a
            # chained adopter death re-targets earlier entries too
            table = dict(dc._recovery_translate or {})
            table.update(dead_map)
            dc.set_rank_translation(table)
            with self._lock:
                if dc not in self._translated:
                    self._translated.append(dc)
        tp.rank_translation = dead_map
        dead_set = set(dead_map)
        tpid = tp.taskpool_id
        # minimal replay applies to enumerable PTG pools with a
        # complete lineage ring; insert-driven DTD pools take the
        # SKIP-AGREEMENT path instead (ghost-replay the agreed
        # prefix); everything else (dynamic discovery, evicted/
        # disabled ring) takes the restore-point fallback
        want_minimal = (self.minimal_on and self.lineage_on
                        and spec["replay"] is None
                        and not getattr(tp, "dynamic", False)
                        and isinstance(tp, ParameterizedTaskpool)
                        and tp._lineage is not None
                        and not tp._lineage.overflow)
        want_skip = (self.minimal_on and self.lineage_on
                     and self.dtd_skip_on
                     and spec["replay"] is not None
                     and callable(getattr(tp, "dtd_skip_report", None))
                     and tp._lineage is not None
                     and not tp._lineage.overflow)
        with self._ctl_cond:
            # (stale votes need no purge here: ballots carry the
            # restart-attempt round, and _agree_mode matches rounds —
            # purging instead would delete a FASTER peer's
            # current-round vote and split the gang's modes)
            self._plan_state[tpid] = "open" if want_minimal else "full"
        if not want_minimal and not want_skip:
            self._broadcast_mode(tpid, False)
        fallback_reason = None
        if not want_minimal and not want_skip:
            fallback_reason = self._static_fallback_reason(tp, spec)
        rplan = synth = base_restores = None
        skip = None
        try:
            # pre-flight: every tile this rank now owns must have a
            # restore source — check BEFORE tearing runtime state down
            plan = self._restore_plan(spec)
            # park inbound activations (state < RUNNING), then fence
            # stale generations (run_epoch) and wait their bodies out
            tp.state = TaskpoolState.ATTACHED
            tp.run_epoch += 1
            jr = self.context.journal
            if jr is not None:
                jr.emit("epoch_fence", pool=tpid, epoch=tp.run_epoch,
                        dead=dead_set)
            # belt only: correctness rides on claim-before-fence-check
            # in task_progress (the drain observes every claimed body);
            # this just skips one drain poll for tasks popped right at
            # the bump
            time.sleep(0.02)
            self._drain_inflight(tp)
            try:
                ctx.sync_devices(timeout=5.0)
            except Exception as exc:
                debug_verbose(2, "recovery device sync: %s", exc)
            # comm: drop the torn generation's parked/queued state
            rde.forget_pool(tp)
            if want_minimal:
                # the lineage is stable now (fence + drain): compute
                # the recorded minimal plan, negotiate cross-survivor
                # re-feeds, and capture every synthesis/rewind payload
                # BEFORE any tile is overwritten
                try:
                    rplan = self._plan_minimal(tp, spec, dead_set)
                    synth, base_restores = \
                        self._materialize_plan(tp, spec, rplan)
                    # MODE AGREEMENT: commit to minimal only when every
                    # live survivor voted minimal too — a full-replaying
                    # peer sends no re-feed needs, and skipping its
                    # producers would strand its re-enumeration forever
                    self._broadcast_mode(tpid, True)
                    if not self._agree_mode(tpid):
                        debug_verbose(1, "rank %d: pool %d minimal "
                                      "replay fell back (a peer took "
                                      "full replay)", ctx.rank, tpid)
                        fallback_reason = "mode-vote full (a peer " \
                                          "took full replay)"
                        rplan = None
                        with self._ctl_cond:
                            self._plan_state[tpid] = "full"
                        self._broadcast_mode(tpid, False)
                except RecoveryUnsupported as why:
                    debug_verbose(1, "rank %d: pool %d minimal replay "
                                  "fell back to restore-point (%s)",
                                  ctx.rank, tpid, why)
                    fallback_reason = str(why)
                    rplan = None
                    with self._ctl_cond:
                        self._plan_state[tpid] = "full"
                    self._broadcast_mode(tpid, False)
            if want_skip:
                # DTD insert-stream skip agreement: evidence is stable
                # now (fence + drain), and the torn generation's comm
                # state is gone — agree the skippable prefix BEFORE
                # the reset discards the landed/seed evidence
                try:
                    skip = self._plan_dtd_skip(tp, spec, dead_set)
                    self._broadcast_mode(tpid, True)
                    if not self._agree_mode(tpid):
                        debug_verbose(1, "rank %d: pool %d DTD skip "
                                      "fell back (skip-vote full on a "
                                      "peer)", ctx.rank, tpid)
                        fallback_reason = "skip-vote full"
                        skip = None
                        self._broadcast_mode(tpid, False)
                except RecoveryUnsupported as why:
                    debug_verbose(1, "rank %d: pool %d DTD skip fell "
                                  "back to full replay (%s)",
                                  ctx.rank, tpid, why)
                    fallback_reason = str(why)
                    skip = None
                    self._broadcast_mode(tpid, False)
            # termdet rewind.  force_terminated: a pool that completed
            # LOCALLY (its partition drained before the kill) must
            # still restart — the adopter's re-executed activations
            # land here — and the returned TERMINATED tells us to
            # re-arm the completion bookkeeping its termination already
            # released
            was = tp.termdet.taskpool_reset(tp, force_terminated=True)
            if jr is not None:
                jr.emit("termdet_rewind", pool=tpid,
                        was=(was.name if was is not None else None),
                        epoch=tp.run_epoch)
            if was is None:
                tp.state = TaskpoolState.DONE
                with self._lock:
                    self._active.discard(tp.taskpool_id)
                return 0
            from parsec_tpu.core.termdet import TermdetState
            if was == TermdetState.TERMINATED:
                with ctx._lock:
                    ctx._active_taskpools += 1
                tp._done_event.clear()
            tp.termdet.taskpool_addto_runtime_actions(tp, 1)  # startup
            if getattr(tp, "_dyn_hold", False):
                # a DynamicTaskpool's distributed termination hold was
                # zeroed with the counters: re-take it (and keep the
                # comm layer's registration) so the restarted pool
                # still resolves through the pool-scoped Safra round
                # instead of stranding resolve_dynamic_holds
                tp.termdet.taskpool_addto_runtime_actions(tp, 1)
                rde.rearm_dynamic_hold(tp)
            tp.recovery_reset()
            if rplan is not None:
                # minimal: restore the adopted partition (its versions
                # died with the rank) and the planned rewinds only —
                # every other local tile keeps its live final state
                tp._replay_filter = set(rplan.tasks)
                for dc, idx, arr in plan:
                    if dc.rank_of(*idx) in dead_set:
                        dc.data_of(*idx).overwrite_host(np.asarray(arr))
                for dc, idx, arr in base_restores:
                    dc.data_of(*idx).overwrite_host(np.asarray(arr))
            elif skip is not None:
                # DTD skip: restore ONLY tiles the agreed prefix never
                # writes (vcut 0 — pool-attach snapshot / init state);
                # every written tile's cut value is the designated
                # holder's live bytes, seeded/served during the replay
                vc = skip["vcut"]
                dcids = getattr(tp, "_dc_ids", {})
                for dc, idx, arr in plan:
                    wire = ("c", dcids.get(id(dc)),
                            dc.data_key(*idx))
                    if wire not in vc:
                        dc.data_of(*idx).overwrite_host(np.asarray(arr))
                tp.dtd_arm_skip(skip["prefix"], skip["holders"],
                                skip["seeds"], vc)
            else:
                # restore the last surviving version of every owned tile
                for dc, idx, arr in plan:
                    dc.data_of(*idx).overwrite_host(np.asarray(arr))
        except Exception:
            # anything failing BEFORE the restore finished leaves the
            # adopted partition unrestored: roll the translation back
            # so no later pool sees zero-filled adopted tiles as local
            # (the pool itself is contained by the caller)
            for dc, prev in prev_tables:
                dc.set_rank_translation(prev)
            raise
        # re-insert the re-execution sub-DAG
        if spec["replay"] is not None:
            spec["replay"](tp)
            if skip is not None:
                # covers the all-skipped stream (no post-prefix insert
                # triggered the finalize) and disarms the filter
                tp.dtd_skip_finish()
            n = max(int(tp.nb_tasks), 0)
        else:
            ready = tp.startup()
            if rplan is not None and synth:
                # deliveries whose producers are skipped: hand the
                # materialized versions straight to the dep countdown
                ready.extend(self._deliver_synth(tp, synth))
            n = max(int(tp.nb_tasks), 0)
            if ready:
                scheduling.schedule(ctx.streams[0], ready)
        jr2 = ctx.journal
        rnd = self._mode_round(tpid)
        if rplan is not None:
            self.minimal_replays += 1
            if jr2 is not None:
                jr2.emit("replay_mode", pool=tpid, mode="minimal",
                         round=rnd, tasks=n, synth=len(synth),
                         rewinds=len(base_restores))
            debug_verbose(1, "rank %d: pool %d MINIMAL replay: %d "
                          "task(s), %d synthesized edge(s), %d "
                          "rewound tile(s)", ctx.rank, tpid, n,
                          len(synth), len(base_restores))
        elif skip is not None:
            self.minimal_replays += 1
            self.skip_agreements += 1
            if jr2 is not None:
                jr2.emit("replay_mode", pool=tpid, mode="skip",
                         round=rnd, prefix=skip["prefix"],
                         seeds=len(skip["seeds"]), tasks=n)
            debug_verbose(1, "rank %d: pool %d DTD MINIMAL replay: "
                          "skipped the agreed insert prefix %d (%d "
                          "held cut payload(s)), %d task(s) re-run",
                          ctx.rank, tpid, skip["prefix"],
                          len(skip["seeds"]), n)
        else:
            self.full_replays += 1
            if jr2 is not None:
                jr2.emit("replay_mode", pool=tpid, mode="full",
                         round=rnd, reason=fallback_reason or "unknown",
                         tasks=n)
            # every full-replay fallback is DIAGNOSABLE from the
            # flight-recorder bundle (reason string: evicted ring /
            # nacked need / skip-vote full / unsupported pool / ...),
            # not inferred from counter deltas
            ctx.telemetry_incident(
                f"recovery-fallback pool={tpid} "
                f"reason={fallback_reason or 'unknown'}")
        tp.ready()
        with self._lock:
            self._active.discard(tp.taskpool_id)
        # frames parked while the pool was down deliver into the new
        # generation now
        rde.retry_delayed()
        drain = getattr(ctx.comm, "dtd_drain_backlog", None)
        if drain is not None and hasattr(tp, "_dtd_incoming"):
            drain(tp)
        return n

    def _static_fallback_reason(self, tp, spec) -> str:
        """Why a pool never even attempts a minimal/skip plan — the
        reason string every full-replay fallback's flight-recorder
        incident carries."""
        if not (self.minimal_on and self.lineage_on):
            return "minimal replay disabled by configuration"
        lin = tp._lineage
        if lin is None:
            return "unsupported pool (no lineage ring armed)"
        if lin.overflow:
            return "evicted ring"
        if spec["replay"] is not None:
            if not self.dtd_skip_on:
                return "dtd skip agreement disabled by configuration"
            return "unsupported pool (replay-driven, no skip report)"
        if getattr(tp, "dynamic", False):
            return "unsupported pool (dynamic discovery)"
        return "unsupported pool"

    # -- dead-set agreement + replay-need negotiation (TAG_RECOVER) ------
    def _agree_dead_set(self, observed: set) -> set:
        """Converge this survivor's dead-set view with the coordinator
        (lowest live rank).  The coordinator coalesces reports for
        ``recovery_agree_window_s`` and broadcasts the CONFIRMED union;
        everyone else reports and waits (bounded) for a broadcast
        covering its observation.  Returns the agreed set; on timeout
        (coordinator died mid-round) the local view — bounded, never
        silent."""
        rde = self._rde
        if rde is None:
            return set(observed)
        ce = rde.ce
        me = ce.rank
        live = [r for r in range(ce.nranks)
                if r != me and r not in ce.dead_peers
                and r not in observed]
        if not live:
            return set(observed)   # sole survivor: nothing to agree
        from parsec_tpu.comm.engine import TAG_RECOVER
        coord = min([me] + live)
        if coord == me:
            deadline = time.monotonic() + self.agree_window
            with self._ctl_cond:
                while True:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._ctl_cond.wait(left)
                reported = set()
                for s in self._agree_reports.values():
                    reported |= s
            confirmed = (set(observed) | reported
                         | set(ce.dead_peers)) - {me}
            with self._ctl_cond:
                self._agree_confirmed.update(confirmed)
            jr = self.context.journal
            if jr is not None:
                jr.emit("deadset_bcast", peers=confirmed)
            for r in sorted(set(range(ce.nranks)) - confirmed - {me}):
                try:
                    ce.send_am(TAG_RECOVER, r,
                               {"k": "deadset",
                                "ranks": sorted(confirmed)})
                except OSError:
                    pass   # its death will get its own event
            return confirmed
        jr = self.context.journal
        if jr is not None:
            jr.emit("deadset_report", peers=observed, coord=coord)
        try:
            ce.send_am(TAG_RECOVER, coord,
                       {"k": "dead", "ranks": sorted(observed)})
        except OSError:
            return set(observed)
        deadline = time.monotonic() + self.agree_timeout
        with self._ctl_cond:
            while not (observed <= self._agree_confirmed):
                left = deadline - time.monotonic()
                if left <= 0:
                    warning("rank %d: dead-set agreement timed out "
                            "waiting for coordinator %d; proceeding "
                            "with the local view %s", me, coord,
                            sorted(observed))
                    if jr is not None:
                        # the bounded degradation, now on the record:
                        # the coordinator died mid-round and this
                        # survivor proceeds on its local view
                        jr.emit("deadset_timeout", peers=observed,
                                coord=coord)
                    return set(observed)
                self._ctl_cond.wait(left)
            return set(observed) | set(self._agree_confirmed)

    def _declare_reported(self, ranks: set, src: int) -> None:
        """A peer's report/broadcast names deaths this rank has not
        detected yet: declare them now so the local recovery event
        fires and every survivor converges on one dead set."""
        rde = self._rde
        if rde is None:
            return
        ce = rde.ce
        from parsec_tpu.core.errors import PeerFailedError
        for r in ranks:
            if r == ce.rank or r == src or r in ce.dead_peers:
                continue
            ce.declare_peer_dead(r, PeerFailedError(
                r, f"rank {ce.rank}: rank {r} reported dead by rank "
                   f"{src} (dead-set agreement)", detector="agreement"))

    # lint: on-loop (TAG_RECOVER AM handler via CommEngine.on_recover)
    def _on_recover_msg(self, src: int, msg: dict) -> None:
        """Recovery control lane (comm thread: store, signal, reply —
        the heavy work stays on the recovery thread)."""
        k = msg.get("k")
        jr = self.context.journal
        if k == "dead":
            ranks = {int(r) for r in msg.get("ranks", ())}
            if jr is not None:
                jr.emit("deadset_recv", peers=ranks, src=src, kind=k)
            with self._ctl_cond:
                self._agree_reports.setdefault(src, set()).update(ranks)
                self._ctl_cond.notify_all()
            self._declare_reported(ranks, src)
        elif k == "deadset":
            ranks = {int(r) for r in msg.get("ranks", ())}
            if jr is not None:
                jr.emit("deadset_recv", peers=ranks, src=src, kind=k)
            with self._ctl_cond:
                self._agree_confirmed.update(ranks)
                self._ctl_cond.notify_all()
            self._declare_reported(ranks, src)
        elif k == "need":
            self._handle_need(src, msg)
        elif k == "need_ack":
            with self._ctl_cond:
                self._need_acks[(msg.get("tp"), src)] = \
                    bool(msg.get("ok"))
                self._ctl_cond.notify_all()
        elif k == "skipf":
            # DTD skip agreement: a survivor's frontier/landed report
            # (or its full vote) — store for the coordinator's round
            if jr is not None:
                jr.emit("skip_offer", pool=msg.get("tp"),
                        round=int(msg.get("round", 0)),
                        frontier=int(msg.get("frontier", -1)),
                        src=src, full=msg.get("full"))
            with self._ctl_cond:
                self._skip_reports[(msg.get("tp"), src)] = \
                    (int(msg.get("round", 0)), msg)
                self._ctl_cond.notify_all()
        elif k == "skipset":
            # the coordinator's agreed-prefix broadcast
            if jr is not None:
                jr.emit("skip_cut", pool=msg.get("tp"),
                        round=int(msg.get("round", 0)),
                        prefix=int(msg.get("prefix", 0)), src=src)
            with self._ctl_cond:
                self._skip_set[msg.get("tp")] = \
                    (int(msg.get("round", 0)), msg)
                self._ctl_cond.notify_all()
        elif k == "retire":
            # retirement handshake: a rank reports local completion
            self._note_retire_report(msg.get("tp"), src)
        elif k == "retired":
            # coordinator confirmed global quiescence for this pool
            self._apply_retired(msg.get("tp"))
        elif k == "mode":
            tpid = msg.get("tp")
            rnd = int(msg.get("round", 0))
            if jr is not None:
                jr.emit("mode_vote", pool=tpid, round=rnd,
                        mode="minimal" if msg.get("minimal") else "full",
                        src=src)
            reply = None
            with self._ctl_cond:
                self._peer_modes[(tpid, src)] = \
                    (rnd, "minimal" if msg.get("minimal") else "full")
                self._ctl_cond.notify_all()
                mine = self._my_mode.get(tpid)
                if mine is not None and mine[0] == rnd \
                        and not msg.get("re"):
                    # answer a late voter with our same-round ballot —
                    # we may have committed and left the agreement
                    # wait already ("re" marks replies: never reply to
                    # a reply, or two committed ranks ping-pong)
                    reply = {"k": "mode", "tp": tpid, "round": rnd,
                             "minimal": mine[1] == "minimal",
                             "re": True}
            if reply is not None and self._rde is not None:
                from parsec_tpu.comm.engine import TAG_RECOVER
                try:
                    self._rde.ce.send_am(TAG_RECOVER, src, reply)
                except OSError:
                    pass

    def _handle_need(self, src: int, msg: dict) -> None:
        """A peer's minimal plan needs producers living here re-run so
        its re-executing consumers are re-fed.  Ack = a PROMISE: the
        resolved producer keys join this rank's replay set before its
        plan freezes.  Nack (plan already frozen, pool not restarting
        here, or unresolvable need) sends the requester to its full-
        replay fallback."""
        tpid = msg.get("tp")
        tp = self.context.taskpools.get(tpid)
        jr = self.context.journal
        if jr is not None:
            jr.emit("need_req", pool=tpid, src=src,
                    n=len(msg.get("needs", ())))
        ok = False
        if tp is not None and self.recovering(tp):
            seeds: List[Any] = []
            resolvable = True
            for ckey, cflow in msg.get("needs", ()):
                got = self._resolve_need(tp, tuple(ckey), cflow)
                if not got:
                    resolvable = False
                    break
                seeds.extend(got)
            if resolvable:
                with self._ctl_cond:
                    state = self._plan_state.get(tpid)
                    if state != "frozen":
                        # "open"/None: merged before the freeze;
                        # "full": everything re-runs anyway
                        self._extra_seeds.setdefault(
                            tpid, set()).update(seeds)
                        ok = True
                    else:
                        # SECOND-ROUND need against a frozen plan (the
                        # requester's merged seed closure widened): ack
                        # without modification iff every resolved
                        # producer is ALREADY in the frozen replay set
                        # — the promise costs nothing, and the r12
                        # unconditional nack forced a full replay for
                        # needs the plan was about to satisfy anyway
                        frozen = self._frozen_tasks.get(tpid)
                        ok = frozen is not None \
                            and all(s in frozen for s in seeds)
        rde = self._rde
        if jr is not None:
            # the answered-or-degraded invariant's responder half:
            # every need_req this rank observed gets its need_ack on
            # the record (a missing pair is an unanswered negotiation)
            jr.emit("need_ack", pool=tpid, dst=src, ok=ok)
        if rde is not None:
            from parsec_tpu.comm.engine import TAG_RECOVER
            try:
                rde.ce.send_am(TAG_RECOVER, src,
                               {"k": "need_ack", "tp": tpid, "ok": ok})
            except OSError:
                pass   # the requester died; its death routes elsewhere

    def _resolve_need(self, tp, ckey: Tuple, cflow: str) -> List[Any]:
        """Structurally invert a consumer's task-fed input edge to the
        producer instance(s) THIS rank owns (the requester only knows
        the consumer side).  Empty list = unresolvable (nack)."""
        from parsec_tpu.core.task import FromTask
        tc = tp.task_classes.get(ckey[0]) if ckey else None
        if tc is None or tc.key_fn is not None:
            return []
        try:
            locals_ = tc.key_to_locals(ckey)
            fl = tc._flow_by_name.get(cflow)
            dep = fl.active_input(locals_) if fl is not None else None
            if dep is None or not isinstance(dep.end, FromTask):
                return []
            ptc = tp.task_classes.get(dep.end.task_class)
            if ptc is None or ptc.key_fn is not None:
                return []
            out = []
            myrank = self.context.rank
            for pl in dep.end.instances(locals_):
                pl = ptc.complete_locals(dict(pl))
                if ptc.rank_of(pl) == myrank:
                    out.append(ptc.make_key(pl))
            return out
        except Exception:
            return []

    def _negotiate_needs(self, tp, needs: List[Tuple[int, Any, str]]) \
            -> bool:
        """Ask each producing survivor to include our needed producers
        in ITS replay set; True only when every peer acked within the
        agreement timeout.  (The journal's ``need_send`` record is the
        caller's — _plan_minimal knows the negotiation round.)"""
        rde = self._rde
        if rde is None:
            return False
        ce = rde.ce
        tpid = tp.taskpool_id
        by_peer: Dict[int, List] = {}
        for r, ckey, cflow in needs:
            by_peer.setdefault(r, []).append((tuple(ckey), cflow))
        from parsec_tpu.comm.engine import TAG_RECOVER
        with self._ctl_cond:
            for r in by_peer:
                self._need_acks.pop((tpid, r), None)
        for r, items in by_peer.items():
            try:
                ce.send_am(TAG_RECOVER, r,
                           {"k": "need", "tp": tpid, "needs": items})
            except OSError:
                return False
        deadline = time.monotonic() + self.agree_timeout
        with self._ctl_cond:
            while True:
                missing = [r for r in by_peer
                           if (tpid, r) not in self._need_acks]
                if not missing:
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._ctl_cond.wait(left)
            return all(self._need_acks.get((tpid, r))
                       for r in by_peer)

    def _mode_round(self, tpid: int) -> int:
        """The mode-vote round = this pool's restart-attempt count —
        symmetric across survivors under the gang-wide restart rule,
        so two ranks agreeing are provably talking about the SAME
        death event (divergent rounds time out into full replay)."""
        with self._lock:
            return self._attempts.get(tpid, 0)

    def _broadcast_mode(self, tpid: int, minimal: bool) -> None:
        """Declare this rank's replay mode for one pool restart to
        every live peer (the mode-agreement vote), and remember it so
        a late voter's ballot gets answered after we committed."""
        rde = self._rde
        if rde is None:
            return
        rnd = self._mode_round(tpid)
        mode = "minimal" if minimal else "full"
        with self._ctl_cond:
            self._my_mode[tpid] = (rnd, mode)
        peers = rde._live_peers()
        jr = self.context.journal
        if jr is not None:
            # membership = this voter's view of the round's live gang
            # (the auditor's votes-agree-on-membership invariant reads
            # exactly this field across ranks)
            jr.emit("mode_decl", pool=tpid, round=rnd, mode=mode,
                    peers=set(peers) | {self.context.rank})
        from parsec_tpu.comm.engine import TAG_RECOVER
        for r in peers:
            try:
                rde.ce.send_am(TAG_RECOVER, r,
                               {"k": "mode", "tp": tpid, "round": rnd,
                                "minimal": bool(minimal)})
            except OSError:
                pass

    def _agree_mode(self, tpid: int) -> bool:
        """Every survivor must take the SAME replay mode for a pool: a
        full-replaying peer sends no re-feed needs, so a minimal peer
        would skip producers that peer's re-enumeration waits on
        forever — asymmetric modes deadlock the gang.  True only when
        EVERY live peer declared minimal FOR THIS ROUND within the
        agreement timeout; a declared full, a missing vote, or a
        divergent round falls this rank back too (full-on-all-sides is
        always safe: it is the r12 policy)."""
        rde = self._rde
        peers = rde._live_peers() if rde is not None else []
        if not peers:
            return True
        rnd = self._mode_round(tpid)
        deadline = time.monotonic() + self.agree_timeout

        def _result(agreed: bool) -> bool:
            jr = self.context.journal
            if jr is not None:
                jr.emit("mode_result", pool=tpid, round=rnd,
                        mode="minimal" if agreed else "full")
            return agreed

        with self._ctl_cond:
            while True:
                modes = [self._peer_modes.get((tpid, r)) for r in peers]
                modes = [m[1] if m is not None and m[0] == rnd else None
                         for m in modes]
                if any(m == "full" for m in modes):
                    return _result(False)
                if all(m == "minimal" for m in modes):
                    return _result(True)
                left = deadline - time.monotonic()
                if left <= 0:
                    return _result(False)
                self._ctl_cond.wait(left)

    # -- DTD insert-stream skip agreement ---------------------------------
    def _plan_dtd_skip(self, tp, spec, dead_set: set) -> dict:
        """Agree the largest common skippable insert-stream prefix for
        one multi-rank DTD pool restart (one TAG_RECOVER report/
        broadcast round, bounded by ``recovery_agree_timeout_s``) and
        materialize this rank's side of it: the cut payloads it is the
        designated holder of, and the vcut map the selective restore
        consults.  A sole survivor short-circuits to its local view
        (no wire round).  Raises :class:`RecoveryUnsupported` on any
        infeasibility — the caller votes full and the PR 11 mode round
        falls the whole gang back symmetrically."""
        from parsec_tpu.comm.engine import TAG_RECOVER
        tpid = tp.taskpool_id
        rep = tp.dtd_skip_report()
        full_why = rep.get("full")
        rde = self._rde
        ce = rde.ce if rde is not None else None
        peers = rde._live_peers() if rde is not None else []
        rnd = self._mode_round(tpid)
        me = self.context.rank
        jr = self.context.journal
        if jr is not None:
            # this rank's OWN offered cut — the auditor checks every
            # agreed prefix against every offer in the round
            jr.emit("skip_offer", pool=tpid, round=rnd,
                    frontier=(-1 if full_why is not None
                              else int(rep["frontier"])),
                    full=full_why)
        if not peers or ce is None:
            # sole survivor: the agreement short-circuits locally
            if full_why is not None:
                raise RecoveryUnsupported(f"dtd skip: {full_why}")
            k, holders, vcuts = dtd_skip_prefix(
                {me: rep["frontier"]}, {me: rep["landed"]},
                rep["writes"])
            if jr is not None:
                jr.emit("skip_cut", pool=tpid, round=rnd, prefix=int(k))
            if k <= 0:
                raise RecoveryUnsupported(
                    "dtd skip: no skippable prefix in the local view")
        elif rde.recovery_coordinator() == ce.rank:
            # coordinator: collect every survivor's frontier report,
            # cut the common prefix, broadcast it (prefix 0 = the gang
            # falls back fast instead of timing out one by one)
            k, holders, vcuts = 0, {}, {}
            why = None
            if full_why is not None:
                why = f"local vote full ({full_why})"
            else:
                deadline = time.monotonic() + self.agree_timeout
                reports: Dict[int, dict] = {}
                with self._ctl_cond:
                    while True:
                        reports = {}
                        for r in peers:
                            ent = self._skip_reports.get((tpid, r))
                            if ent is not None and ent[0] == rnd:
                                reports[r] = ent[1]
                        if len(reports) == len(peers):
                            break
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        self._ctl_cond.wait(left)
                if len(reports) < len(peers):
                    why = "a survivor's skip report never arrived"
                else:
                    fulls = sorted(r for r, m in reports.items()
                                   if m.get("full"))
                    if fulls:
                        why = (f"rank {fulls[0]} voted full "
                               f"({reports[fulls[0]]['full']})")
                    else:
                        frontiers = {me: rep["frontier"]}
                        landed = {me: dict(rep["landed"])}
                        for r, m in reports.items():
                            frontiers[r] = int(m["frontier"])
                            landed[r] = dict(m["landed"])
                        k, holders, vcuts = dtd_skip_prefix(
                            frontiers, landed, rep["writes"])
                        if k <= 0:
                            why = ("no common prefix consistent with "
                                   "the survivors' materializable cuts")
            out = {"k": "skipset", "tp": tpid, "round": rnd,
                   "prefix": k, "holders": holders, "vcut": vcuts}
            if jr is not None:
                jr.emit("skip_cut", pool=tpid, round=rnd, prefix=int(k),
                        why=why)
            for r in peers:
                try:
                    ce.send_am(TAG_RECOVER, r, dict(out))
                except OSError:
                    pass   # its death gets its own event
            if why is not None:
                raise RecoveryUnsupported(f"dtd skip: {why}")
        else:
            # participant: report the frontier (or the full vote),
            # then wait for the coordinator's agreed prefix
            coord = rde.recovery_coordinator()
            msg = {"k": "skipf", "tp": tpid, "round": rnd}
            if full_why is not None:
                msg["full"] = full_why
            else:
                msg["frontier"] = rep["frontier"]
                msg["landed"] = rep["landed"]
            try:
                ce.send_am(TAG_RECOVER, coord, msg)
            except OSError:
                raise RecoveryUnsupported(
                    "dtd skip: coordinator unreachable")
            if full_why is not None:
                raise RecoveryUnsupported(f"dtd skip: {full_why}")
            deadline = time.monotonic() + self.agree_timeout
            with self._ctl_cond:
                while True:
                    ent = self._skip_set.get(tpid)
                    if ent is not None and ent[0] == rnd:
                        agreed = ent[1]
                        break
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise RecoveryUnsupported(
                            "dtd skip: agreed-prefix broadcast never "
                            "arrived (coordinator died mid-round?)")
                    self._ctl_cond.wait(left)
            k = int(agreed.get("prefix", 0))
            holders = dict(agreed.get("holders") or {})
            vcuts = dict(agreed.get("vcut") or {})
            if k <= 0:
                raise RecoveryUnsupported(
                    "dtd skip: coordinator declared no skippable "
                    "prefix")
        mine = [w for w, h in holders.items() if h == me]
        seeds = tp.dtd_capture_seeds(mine)
        if len(seeds) != len(mine):
            # a held cut payload with no host bytes is an
            # infeasibility, not a crash — the mode round falls the
            # gang back symmetrically
            raise RecoveryUnsupported(
                "dtd skip: a held cut payload is not host-pullable")
        return {"prefix": k, "holders": holders, "vcut": vcuts,
                "seeds": seeds}

    # -- minimal replay (recorded-lineage plan) ---------------------------
    def _plan_minimal(self, tp, spec, dead_set: set) -> ReplayPlan:
        """Compute, negotiate, and FREEZE the minimal plan for one pool
        restart.  Raises RecoveryUnsupported on any infeasibility — the
        caller then takes the restore-point fallback."""
        tpid = tp.taskpool_id
        rounds = self.need_rounds_cap
        used = 0
        counts = self.need_round_counts

        def _round_outcome(outcome: str, rnd: int, peers) -> None:
            """Count AND journal one negotiation round's terminal
            outcome — a silent round is exactly the bug class the
            auditor's answered-or-degraded invariant encodes."""
            counts[outcome] += 1
            jr = self.context.journal
            if jr is not None:
                jr.emit("need_round", pool=tpid, round=rnd,
                        outcome=outcome, peers=peers)

        def _round_send(rnd: int, needs) -> None:
            jr = self.context.journal
            if jr is not None:
                jr.emit("need_send", pool=tpid, round=rnd,
                        peers={r for r, _k, _f in needs},
                        n=len(needs))

        with self._ctl_cond:
            extra = set(self._extra_seeds.get(tpid, ()))
        plan = self._compute_minimal(tp, spec, dead_set, extra)
        first_needs = {(r, k, f) for r, k, f in plan.needs}
        if plan.needs:
            used = 1
            need_peers = {r for r, _k, _f in plan.needs}
            _round_send(1, plan.needs)
            if not self._negotiate_needs(tp, plan.needs):
                _round_outcome("nacked", 1, need_peers)
                raise RecoveryUnsupported(
                    "a peer nacked (or never acked) a re-feed need")
            _round_outcome("acked", 1, need_peers)
        if self._rde is not None and self._rde._live_peers():
            # one window for LATE cross-survivor needs to land before
            # the plan freezes (peers restarting the same pool send
            # theirs concurrently)
            time.sleep(min(self.agree_window, 1.0))
        with self._ctl_cond:
            self._plan_state[tpid] = "frozen"
            # published so a peer's OWN widened second-round need can
            # ack against this rank's committed replay set
            self._frozen_tasks[tpid] = set(plan.tasks)
            extra2 = set(self._extra_seeds.pop(tpid, ()))
        if extra2 - extra:
            plan = self._compute_minimal(tp, spec, dead_set, extra2)
            with self._ctl_cond:
                self._frozen_tasks[tpid] = set(plan.tasks)
            widened = {(r, k, f) for r, k, f in plan.needs} \
                - first_needs
            if widened:
                # the merged seeds' closure reached producers nobody
                # asked for: re-issue a BOUNDED second need round
                # against the peers' frozen plans (they ack iff the
                # producers are already committed) instead of the r12
                # unconditional fallback
                wide_peers = {r for r, _k, _f in widened}
                if used >= rounds:
                    _round_outcome("exhausted", used + 1, wide_peers)
                    raise RecoveryUnsupported(
                        "merged re-feed seeds widened the remote needs "
                        f"past recovery_need_rounds={rounds}")
                used += 1
                _round_outcome("widened", used, wide_peers)
                _round_send(used, sorted(widened))
                if not self._negotiate_needs(tp, sorted(widened)):
                    _round_outcome("nacked", used, wide_peers)
                    raise RecoveryUnsupported(
                        "a peer nacked a widened re-feed need "
                        "(second negotiation round)")
                _round_outcome("acked", used, wide_peers)
        return plan

    def _compute_minimal(self, tp, spec, dead_set: set,
                         extra_seeds: set) -> ReplayPlan:
        """Adapter feeding :func:`minimal_plan`: enumerate the local +
        adopted instance space, derive structural edges from the task
        classes, and expose live/materializable tile versions."""
        from parsec_tpu.core.task import FromDesc, FromTask
        lin = tp._lineage
        if lin is None or lin.overflow:
            raise RecoveryUnsupported(
                "lineage ring evicted records (or recording disabled)")
        if any(tc.key_fn is not None
               for tc in tp.task_classes.values()):
            raise RecoveryUnsupported(
                "custom key_fn task class: keys are not invertible")
        myrank = self.context.rank
        records = list(lin.records)
        completed = set(lin.completed)
        dcs = {dc.name: dc for dc in spec["collections"]}
        with self._lock:
            snaps = {dc.name: dict(self._snaps.get(id(dc), ()))
                     for dc in spec["collections"]}
        #: key -> (tc, locals, original owner rank)
        keymap: Dict[Any, Tuple] = {}
        pending: set = set()
        adopted: set = set()
        for tc in tp.task_classes.values():
            aff = tc.affinity
            if aff is None and myrank != 0:
                continue
            for locals_ in tc.iter_space(tp.globals):
                locals_ = dict(locals_)
                if aff is not None and tc.rank_of(locals_) != myrank:
                    continue
                key = tc.make_key(locals_)
                orank = 0
                if aff is not None:
                    ref = aff(locals_)
                    orank = ref.dc.rank_of(*ref.indices)
                keymap[key] = (tc, locals_, orank)
                if orank in dead_set:
                    adopted.add(key)
                elif key not in completed:
                    pending.add(key)
        live: Dict[Any, int] = {}
        mat: Dict[Any, set] = {}

        def tile_info(tile) -> None:
            if tile in live:
                return
            dc = dcs.get(tile[0])
            if dc is None:
                return
            idx = tuple(tile[1:])
            if dc.rank_of(*idx) in dead_set:
                return   # adopted partition: restored, not "live"
            try:
                d = dc.data_of(*idx)
            except KeyError:
                return
            live[tile] = d.newest_version()
            vs = set()
            if self.ckpt is not None:
                vs.update(self.ckpt.versions((id(dc), tile)))
            sv = snaps.get(tile[0], {}).get(idx)
            if sv is not None:
                vs.add(sv[0])
            mat[tile] = vs

        for r in records:
            for t, _v in r.reads:
                tile_info(t)
            for t, _v in r.writes:
                tile_info(t)

        def edges(key):
            ent = keymap.get(key)
            if ent is None:
                return
            tc, locals_, _orank = ent
            for flow in tc._in_flows:
                dep = flow.active_input(locals_)
                if dep is None:
                    continue
                end = dep.end
                if isinstance(end, FromTask):
                    if dep.multiplicity(locals_) == 0:
                        continue
                    ptc = tp.task_classes.get(end.task_class)
                    if ptc is None:
                        continue
                    for pl in end.instances(locals_):
                        pl = ptc.complete_locals(dict(pl))
                        pkey = ptc.make_key(pl)
                        porig = 0
                        paff = ptc.affinity
                        if paff is not None:
                            pref = paff(pl)
                            porig = pref.dc.rank_of(*pref.indices)
                        powner = ptc.rank_of(pl)
                        if powner == myrank:
                            where = "local"
                        elif porig in dead_set:
                            where = "dead"
                        else:
                            where = ("peer", powner)
                        yield ("task", pkey, end.flow, flow.name,
                               where, flow.is_ctl)
                elif isinstance(end, FromDesc):
                    from parsec_tpu.data.data import ACCESS_READ
                    if not flow.access & ACCESS_READ:
                        # a WRITE-only desc binding fully overwrites
                        # the tile: no version requirement, no rewind
                        continue
                    ref = end.ref_fn(locals_)
                    if ref.dc.rank_of(*ref.indices) in dead_set:
                        continue   # restored by the adopted path
                    idx = tuple(ref.indices)
                    tile = ref.dc.tile_key(*idx)
                    tile_info(tile)
                    sv = snaps.get(ref.dc.name, {}).get(idx)
                    yield ("desc", tile,
                           sv[0] if sv is not None else None)

        return minimal_plan(
            records, dead_set=dead_set, pending=pending,
            adopted=adopted, live=live, materializable=mat,
            edges=edges, extra_seeds=extra_seeds & set(keymap))

    def _materialize_plan(self, tp, spec, rplan: ReplayPlan):
        """Capture every synthesis payload and rewind array BEFORE any
        tile is overwritten (a rewound tile's live payload may itself
        be a synthesis source)."""
        dcs = {dc.name: dc for dc in spec["collections"]}
        with self._lock:
            snaps = {dc.name: dict(self._snaps.get(id(dc), ()))
                     for dc in spec["collections"]}

        def mater(tile, ver) -> np.ndarray:
            dc = dcs.get(tile[0])
            if dc is None:
                raise RecoveryUnsupported(
                    f"minimal replay: unknown collection for {tile!r}")
            idx = tuple(tile[1:])
            d = dc.data_of(*idx)
            if d.newest_version() == ver:
                copy = d.pull_to_host()
                if copy is None or copy.payload is None:
                    # a pull that cannot produce host bytes is an
                    # infeasibility, not a crash: the caller still has
                    # the full-replay fallback
                    raise RecoveryUnsupported(
                        f"minimal replay: {tile!r} v{ver} has no "
                        "host-pullable payload")
                return np.array(copy.payload, copy=True)
            if self.ckpt is not None:
                arr = self.ckpt.get((id(dc), tile), ver)
                if arr is not None:
                    return arr.copy()
            sv = snaps.get(tile[0], {}).get(idx)
            if sv is not None and sv[0] == ver:
                return np.array(sv[1], copy=True)
            raise RecoveryUnsupported(
                f"minimal replay: {tile!r} v{ver} no longer "
                "materializable")

        synth = []
        for (ckey, cflow, tile, ver, _pk) in rplan.synth:
            arr = None if tile is None else mater(tile, ver)
            synth.append((ckey, cflow, arr))
        base = []
        for tile, ver in rplan.base.items():
            base.append((dcs[tile[0]], tuple(tile[1:]),
                         mater(tile, ver)))
        return synth, base

    def _deliver_synth(self, tp, synth) -> List[Any]:
        """Deliver the materialized out-of-plan-producer edges into the
        restarted dep countdown (exactly how a remote payload lands —
        one fresh datum per delivery; core/engine.deliver_dep returns
        the task when the arrival completes it)."""
        from parsec_tpu.core import engine as core_engine
        from parsec_tpu.data.data import Coherency, Data
        ready = []
        for ckey, cflow, arr in synth:
            tc = tp.task_classes.get(ckey[0])
            if tc is None:
                continue
            locals_ = tc.key_to_locals(ckey)
            copy = None
            if arr is not None:
                datum = Data(nb_elts=arr.nbytes)
                copy = datum.create_copy(0, payload=arr,
                                         coherency=Coherency.SHARED,
                                         version=1)
            t = core_engine.deliver_dep(tp, tc, locals_, cflow, copy,
                                        None)
            if t is not None:
                ready.append(t)
        return ready

    def _restore_plan(self, spec) -> List[Tuple[Any, Tuple, Any]]:
        """(dc, idx, payload) for every tile this rank serves after the
        re-mapping; raises RecoveryUnsupported when a tile has neither a
        snapshot nor a re-runnable source."""
        plan: List[Tuple[Any, Tuple, Any]] = []
        for dc in spec["collections"]:
            if not hasattr(dc, "local_tiles"):
                raise RecoveryUnsupported(
                    f"collection {dc.name!r} has no local_tiles "
                    "enumeration")
            with self._lock:
                snap = dict(self._snaps.get(id(dc), ()))
            for idx in dc.local_tiles():
                idx = tuple(idx) if isinstance(idx, (tuple, list)) \
                    else (idx,)
                if idx in snap:
                    plan.append((dc, idx, snap[idx][1]))
                elif dc.init_fn is not None:
                    plan.append((dc, idx, dc.init_fn(*idx)))
                else:
                    raise RecoveryUnsupported(
                        f"{dc.name}{idx}: no surviving snapshot and no "
                        "init_fn re-runnable source (set one with "
                        "collection.set_init)")
        return plan

    def _drain_inflight(self, tp: Taskpool) -> None:
        """Wait (bounded) until no worker stream is still executing a
        stale-generation body of this pool: their in-place tile writes
        must land BEFORE the restore overwrites them, never after.  A
        drain that cannot complete ABORTS the recovery (the caller
        contains the pool): restoring under a still-running stale body
        would be silent corruption, strictly worse than the contained
        failure recovery replaces."""
        deadline = time.monotonic() + self.drain_s
        while time.monotonic() < deadline:
            busy = False
            for es in self.context.streams:
                t = es.running_task
                if t is not None and t.taskpool is tp \
                        and t.pool_epoch != tp.run_epoch:
                    busy = True
                    break
            if not busy:
                return
            time.sleep(0.005)
        raise RecoveryUnsupported(
            f"rank {self.context.rank}: stale-generation bodies of "
            f"pool {tp.taskpool_id} still running after "
            f"{self.drain_s:g}s drain — restoring under them would "
            "corrupt the lineage base")

    # -- rejoin ----------------------------------------------------------
    def on_rejoin_request(self, src: int, msg: dict) -> Optional[dict]:
        """Survivor side of the rejoin handshake (comm thread): validate
        the incarnation epoch against the fence, clear the dead mark,
        hand back the translation table.  Returns the ack payload, or
        None to deny."""
        rde = self._rde
        if rde is None:
            return None
        epoch = int(msg.get("epoch", 0))
        fence = rde.peer_fence(src)
        jr = self.context.journal
        if epoch < fence:
            if jr is not None:
                jr.emit("rejoin_req", src=src, epoch=epoch, ok=False,
                        fence=fence)
            warning("rank %d: rejected rejoin of rank %d with stale "
                    "epoch %d (fence %d)", self.context.rank, src,
                    epoch, fence)
            return None
        if jr is not None:
            jr.emit("rejoin_req", src=src, epoch=epoch, ok=True,
                    fence=fence)
        rde.note_peer_epoch(src, epoch)
        rde.ce.peer_rejoined(src, epoch)
        with self._ctl_cond:
            # the agreement plane must not re-declare a rejoined rank
            # from stale confirmations of its previous incarnation
            self._agree_confirmed.discard(src)
            self._agree_reports.pop(src, None)
            for s in self._agree_reports.values():
                s.discard(src)
        busy = False
        with self._lock:
            self._peer_epochs[src] = epoch
            self._dead_map.pop(src, None)
            dead_map = dict(self._dead_map)
            busy = bool(self._active) or bool(self._events)
            translated = list(self._translated)
            if busy:
                # a restart mid-flight keeps its table until done (the
                # re-executing tasks must keep resolving to their
                # adopter); the recovery thread applies the clear once
                # the pipeline drains (_apply_untranslate)
                self._pending_untranslate.add(src)
        if not busy:
            # the rank takes its partition back for FUTURE lookups
            for dc in translated:
                table = dict(dc._recovery_translate or {})
                table.pop(src, None)
                dc.set_rank_translation(table)
        self.rejoins += 1
        self._notify_services("rejoin", src)
        warning("rank %d: rank %d REJOINED (incarnation epoch %d)",
                self.context.rank, src, epoch)
        ce = rde.ce
        with ce._bar_cond:
            bar_gen = ce._bar_gen
        return {"k": "ack", "epoch": epoch, "rank": self.context.rank,
                "translation": dead_map, "bar_gen": bar_gen}

    def rejoin(self, timeout: float = 30.0) -> Dict[int, int]:
        """Restarted-rank side: announce the new incarnation to every
        live peer and wait for the first ack; returns the received
        translation table (other still-dead ranks' re-mappings)."""
        rde = self._rde
        if rde is None:
            raise RuntimeError("rejoin needs an attached comm engine")
        ce = rde.ce
        peers = [r for r in range(ce.nranks)
                 if r != ce.rank and r not in ce.dead_peers]
        if not peers:
            raise RuntimeError("rejoin: no live peers to rejoin")
        from parsec_tpu.comm.engine import TAG_REJOIN
        req = {"k": "req", "rank": ce.rank, "epoch": ce.epoch}
        deadline = time.monotonic() + timeout
        ack = None
        while ack is None:
            # RE-ANNOUNCE each round: a frame sent before a survivor
            # finished re-creating its transport state for us (the shm
            # ring re-creation race, a still-dialing socket) is lost —
            # the request is idempotent, so retry until acked
            for r in peers:
                try:
                    ce.send_am(TAG_REJOIN, r, dict(req))
                except OSError:
                    continue   # that survivor died meanwhile
            left = deadline - time.monotonic()
            if left <= 0:
                break
            ack = ce.wait_rejoin_ack(min(2.0, left))
        if ack is None:
            raise TimeoutError(
                f"rank {ce.rank}: rejoin not acknowledged within "
                f"{timeout:g}s (every survivor denied the epoch or was "
                "unreachable)")
        table = {int(k): int(v)
                 for k, v in (ack.get("translation") or {}).items()}
        jr = self.context.journal
        if jr is not None:
            jr.emit("rejoin_done", epoch=ce.epoch,
                    acked_by=int(ack.get("rank", -1)),
                    bar_gen=int(ack.get("bar_gen", 0)))
        with self._lock:
            self._dead_map.update(table)
        # generation-numbered state transfer: the fresh engine's barrier
        # counter syncs to the survivors' so the next collective round
        # numbers match across the rebuilt gang
        with ce._bar_cond:
            ce._bar_gen = max(ce._bar_gen,
                              int(ack.get("bar_gen", 0)))
        return table

    # -- observability ----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                **self.counts,
                "tasks_reexecuted": self.tasks_reexecuted,
                "rejoins": self.rejoins,
                "minimal_replays": self.minimal_replays,
                "full_replays": self.full_replays,
                "skip_agreements": self.skip_agreements,
                "retirements": self.retirements,
                "retire_degraded": self.retire_degraded,
                "need_rounds": dict(self.need_round_counts),
                "dead_map": dict(self._dead_map),
                "active_pools": sorted(self._active),
            }

    def _collect(self) -> List[dict]:
        """Scrape-time metrics families (prof/metrics.py collector —
        zero hot-path hooks; every value accumulates on the recovery/
        comm threads and is read here)."""
        from parsec_tpu.prof.metrics import (counter_sample,
                                             histogram_sample)
        out = [counter_sample("parsec_recoveries_total", v,
                              {"stage": stage})
               for stage, v in self.counts.items()]
        out.append(counter_sample("parsec_tasks_reexecuted_total",
                                  self.tasks_reexecuted))
        out.append(counter_sample("parsec_rank_rejoins_total",
                                  self.rejoins))
        out.append(counter_sample("parsec_recovery_minimal_replays_total",
                                  self.minimal_replays))
        out.append(counter_sample("parsec_recovery_full_replays_total",
                                  self.full_replays))
        out.append(counter_sample("parsec_recovery_skip_agreements_total",
                                  self.skip_agreements))
        out.append(counter_sample(
            "parsec_recovery_pool_retirements_total", self.retirements))
        out.append(counter_sample(
            "parsec_recovery_retire_degraded_total",
            self.retire_degraded))
        out.extend(counter_sample("parsec_recovery_need_rounds_total",
                                  v, {"outcome": outcome})
                   for outcome, v in self.need_round_counts.items())
        out.append(histogram_sample("parsec_recovery_duration_seconds",
                                    self.duration_hist))
        return out
