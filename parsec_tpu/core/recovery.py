"""Recovery plane: lineage re-execution, partition re-mapping, rejoin.

PR 5 finished the failure lifecycle at CONTAINMENT: a dead rank is
detected (EOF / corruption / heartbeat silence), the taskpools touching
it fail with structured errors, and the service degrades permanently.
This module adds the second exit from every containment path — RECOVER:

  1. **Lineage re-execution.**  When ``declare_peer_dead`` fires, the
     surviving ranks reconstruct the dead rank's lost tiles instead of
     failing the pool.  Each survivor deterministically computes the
     same recovery decision (coordinator = lowest surviving rank, but
     the per-rank work needs no election round: translation targets and
     partitions are pure functions of the dead set), rewinds the
     affected pool's termdet counters (``taskpool_reset``), restores the
     pool's collections to their last surviving version — the
     registration-time snapshot, or the collection's re-runnable source
     (``DataCollection.set_init``) for tiles whose only copy died with
     their rank — and re-inserts the re-execution sub-DAG on the
     survivors (``ParameterizedTaskpool.startup`` re-enumeration with
     translated owner-computes, or the pool's ``recovery_replay`` for
     insert-driven DTD pools).  ``lineage_plan`` below is the exact
     minimal-set walk over a recorded lineage; the end-to-end restart
     is deliberately CONSERVATIVE — it replays the pool's whole local
     partition from the restore point, because in-place tile mutation
     means a partial replay is only sound from a globally consistent
     cut (which the registration snapshot / checkpoint shard is, and
     arbitrary mid-run tile states are not).  The ≤2x-makespan
     acceptance bound is the bound of exactly this policy.

  2. **Partition re-mapping.**  The dead rank's key range re-balances
     onto survivors through a rank-translation table installed PER
     COLLECTION (``DataCollection.set_rank_translation``): ``rank_of``
     stays the pure distribution function while ``owner_of`` — which
     task placement, activation routing, and local-tile materialization
     consult — routes around the hole.  Pools over untouched
     collections never observe a re-mapped owner, so silent
     misdirection of unaffected jobs is structurally impossible.

  3. **Elastic rejoin.**  A restarted rank comes back with a bumped
     incarnation epoch (``--mca comm_epoch`` / ``PARSEC_COMM_EPOCH``),
     re-dials the transports, and performs a TAG_REJOIN handshake: the
     survivors validate the epoch against the fence recorded at death
     (stale frames of the previous incarnation are dropped before they
     can touch the Safra balance — see RemoteDepEngine), clear the dead
     mark, hand back the current translation table, and the rank takes
     its partition back for every subsequently attached pool.  Clock
     sync re-establishes through the ordinary TAG_CLOCK probe rounds on
     the re-dialed connection.

Safra/termdet reconciliation: the remote-dep engine keeps per-peer send
and receive counters next to the global balance; a recovery subtracts
the dead rank's whole contribution in one critical section (the same
contract ``faultinject.on_frame_fault`` established for injected drops)
and fences later frames from the dead incarnation, so the token sees
exactly the in-flight traffic among survivors and termination converges
after re-insertion.

Everything here is OPT-IN (``recovery_enable``, default 0): disabled,
every path reproduces PR 5's containment behavior exactly.

Known limits (documented, structured-failure fallbacks): DynamicTaskpool
(PTG ``%option dynamic``) pools, pools whose collections lack both a
snapshot and an ``init_fn`` for the adopted tiles, cancelled pools, and
a rank's own injected death are not recovered; rejoin is supported on
the socket transports (threads/evloop) — an shm receiver unlinks its
rings at death, so a restarted shm rank needs a fresh gang instead.
Under NEAR-SIMULTANEOUS multi-rank deaths, survivors whose detectors
fire in different orders transiently compute divergent translation
tables (each is a pure function of that survivor's dead SET, which
converges as detections land); a restart run against the stale view
can address a just-dead adopter, fail contained, and burn one
``recovery_max_attempts`` slot before the next event re-normalizes —
bounded, never silent, but a true agreement round is future work.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from parsec_tpu.core.taskpool import (ParameterizedTaskpool, Taskpool,
                                      TaskpoolState)
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose, warning

params.register("recovery_max_attempts", 2,
                "per-taskpool budget of peer-death recoveries: one more "
                "rank dying after this many restarts fails the pool "
                "with the contained structured error instead of "
                "recovering again (survivor exhaustion stays a CLEAN "
                "failure, never a loop)")
params.register("recovery_snapshot", 1,
                "snapshot each registered pool's local collection tiles "
                "at attach — the lineage restore point for the pool's "
                "own partition (a dead rank's ADOPTED tiles restore "
                "from the collection's init_fn re-runnable source).  "
                "0 relies on init_fn alone")
params.register("recovery_drain_s", 10.0,
                "bound on waiting for in-flight stale-generation task "
                "bodies to leave the workers before tiles are restored "
                "(the run_epoch fence discards them at completion; this "
                "wait keeps their in-place writes off restored data)")
params.register("recovery_rejoin", 1,
                "accept TAG_REJOIN handshakes from restarted "
                "incarnations of dead ranks (needs recovery_enable; "
                "0 keeps the PR 3 zombie-reconnect rejection)")
params.register("recovery_completed_grace_s", 30.0,
                "how long a LOCALLY-completed pool stays restartable "
                "after its termination: within the window a peer death "
                "still restarts it (another survivor may need its "
                "re-executed partition — local completion is not "
                "global), past it the pool's recovery spec and tile "
                "snapshots are evicted, so a resident service's job "
                "history is never resurrected or leaked")


class RecoveryUnsupported(RuntimeError):
    """A pool or collection cannot be recovered (no snapshot, no
    re-runnable source, unsupported pool type); the peer death then
    takes the containment path with this as context."""


# ---------------------------------------------------------------------------
# lineage planning (pure; unit-tested on hand-built DAGs)
# ---------------------------------------------------------------------------

class LineageRecord:
    """One completed task in a lineage log: the tile versions it read
    and the tile versions it produced (versions are per-tile monotone,
    the datum version-clock discipline)."""

    __slots__ = ("key", "reads", "writes")

    def __init__(self, key: Any,
                 reads: List[Tuple[Any, int]] = (),
                 writes: List[Tuple[Any, int]] = ()):
        self.key = key
        self.reads = list(reads)
        self.writes = list(writes)


def lineage_plan(log: List[LineageRecord],
                 surviving: Dict[Any, int],
                 needed: Dict[Any, int]):
    """The minimal re-execution set: walk backward from the ``needed``
    (tile -> version) outputs to the last surviving version of every
    input.

    ``surviving`` maps tile -> highest version still materialized on a
    live rank (registration snapshots are version 0 of every tile).  A
    needed (tile, version) with ``surviving[tile] >= version`` costs
    nothing; otherwise its producer joins the plan and that producer's
    reads become needed.  Returns ``(tasks, base)``: the re-execution
    set in log (= valid topological) order, and the {tile: version}
    frontier the restore must materialize before replay starts.
    """
    producer: Dict[Tuple[Any, int], int] = {}
    for i, rec in enumerate(log):
        for tile, ver in rec.writes:
            producer[(tile, ver)] = i
    chosen: set = set()
    base: Dict[Any, int] = {}
    work = deque((t, v) for t, v in needed.items())
    seen: set = set()
    while work:
        tile, ver = work.popleft()
        if (tile, ver) in seen:
            continue
        seen.add((tile, ver))
        if surviving.get(tile, -1) >= ver:
            base[tile] = max(base.get(tile, -1), min(ver,
                                                     surviving[tile]))
            continue
        idx = producer.get((tile, ver))
        if idx is None:
            raise RecoveryUnsupported(
                f"lineage broken: no producer and no surviving copy of "
                f"{tile!r} v{ver}")
        if idx in chosen:
            continue
        chosen.add(idx)
        for r in log[idx].reads:
            work.append(r)
    return [log[i].key for i in sorted(chosen)], base


# ---------------------------------------------------------------------------
# the coordinator
# ---------------------------------------------------------------------------

class RecoveryCoordinator:
    """Per-context recovery driver (``Context.recovery``).

    Containment hands it peer deaths on the comm thread
    (``on_peer_dead``); the actual restart work runs on a dedicated
    recovery thread so the transport loop keeps beating hearts while
    tiles restore.  All mutable state is guarded by ``_lock``; the
    restart pipeline itself is serialized by the single worker thread.
    """

    def __init__(self, context):
        self.context = context
        self.enabled = True
        self.max_attempts = int(params.get("recovery_max_attempts", 2))
        self.snapshot_on = bool(int(params.get("recovery_snapshot", 1)))
        self.drain_s = float(params.get("recovery_drain_s", 10.0))
        self.completed_grace = float(
            params.get("recovery_completed_grace_s", 30.0))
        self._lock = threading.Lock()
        self._rde = None               # RemoteDepEngine (attach_comm)
        #: taskpool_id -> {"tp", "collections", "replay"}
        #: (guarded-by: _lock)
        self._specs: Dict[int, Dict[str, Any]] = {}
        #: collection snapshots: id(dc) -> {idx: ndarray}
        #: (guarded-by: _lock)
        self._snaps: Dict[int, Dict[Tuple, np.ndarray]] = {}
        self._snap_dcs: Dict[int, Any] = {}      # keep dc alive w/ snap
        self._attempts: Dict[int, int] = {}      # guarded-by: _lock
        self._active: set = set()                # guarded-by: _lock
        self._events: deque = deque()            # guarded-by: _lock
        self._worker: Optional[threading.Thread] = None  # guarded-by: _lock
        #: dead rank -> adopting survivor, cluster-wide view
        #: (guarded-by: _lock)
        self._dead_map: Dict[int, int] = {}
        #: deaths accepted but not yet processed by the recovery thread
        #: — excused() must cover them, or the window between
        #: on_peer_dead and _process_event routes secondary send
        #: failures into containment and fails the very pool being
        #: rebuilt (guarded-by: _lock)
        self._pending_dead: set = set()
        self._translated: List[Any] = []         # guarded-by: _lock
        #: rejoined incarnation epochs (guarded-by: _lock)
        self._peer_epochs: Dict[int, int] = {}
        #: rejoins that landed while a restart was active: their
        #: translation entries clear once the restart pipeline drains
        #: (guarded-by: _lock)
        self._pending_untranslate: set = set()
        self._services: List[Any] = []           # guarded-by: _lock
        # observability (metrics plane reads these at scrape; the
        # counters move only on the recovery/comm threads)
        self.counts = {"started": 0, "completed": 0, "failed": 0}
        self.tasks_reexecuted = 0
        self.rejoins = 0
        from parsec_tpu.prof.metrics import Histogram
        self.duration_hist = Histogram()
        m = getattr(context, "metrics", None)
        if m is not None:
            m.register_collector(self._collect)

    # -- wiring ----------------------------------------------------------
    def attach_comm(self, rde) -> None:
        """Called by RemoteDepEngine at construction: wire the rejoin
        handshake and let the transport accept reconnections from dead
        ranks (the recovery knob gates it)."""
        self._rde = rde
        if int(params.get("recovery_rejoin", 1)):
            rde.ce.rejoin_allowed = True
            rde.ce.on_rejoin = self.on_rejoin_request

    def attach_service(self, service) -> None:
        with self._lock:
            self._services.append(service)

    def detach_service(self, service) -> None:
        with self._lock:
            if service in self._services:
                self._services.remove(service)

    def _notify_services(self, event: str, rank: int) -> None:
        with self._lock:
            services = list(self._services)
        for svc in services:
            try:
                svc.note_recovery(event, rank)
            except Exception as exc:
                debug_verbose(2, "recovery service notify: %s", exc)

    # -- registration ----------------------------------------------------
    def register_pool(self, tp: Taskpool) -> None:
        """Record a pool's recovery spec at attach and snapshot its
        collections' local tiles — the lineage restore point.  A pool
        without collections stays on the containment path."""
        collections = list(getattr(tp, "recovery_collections", ()) or ())
        spec = {"tp": tp, "collections": collections,
                "replay": getattr(tp, "recovery_replay", None),
                "completed_at": None}
        if collections:
            tp.on_complete(self._pool_done)
        snaps = []
        if collections and self.snapshot_on:
            for dc in collections:
                if not hasattr(dc, "local_tiles"):
                    continue
                snap: Dict[Tuple, np.ndarray] = {}
                try:
                    for idx in dc.local_tiles():
                        idx = tuple(idx) if isinstance(idx, (tuple, list)) \
                            else (idx,)
                        copy = dc.data_of(*idx).pull_to_host()
                        if copy is not None and copy.payload is not None:
                            snap[idx] = np.array(copy.payload, copy=True)
                except Exception as exc:
                    warning("recovery: snapshot of %s failed (%s); "
                            "relying on init_fn", dc.name, exc)
                    snap = {}
                snaps.append((dc, snap))
        with self._lock:
            self._specs[tp.taskpool_id] = spec
            for dc, snap in snaps:
                # latest registration wins: for sequential pools over
                # one collection the snapshot must reflect the state at
                # THIS pool's attach (its replay base), not the first's
                self._snaps[id(dc)] = snap
                self._snap_dcs[id(dc)] = dc
            self._sweep_locked()

    def _pool_done(self, tp) -> None:
        """Completion callback: stamp the grace-window clock (a restart
        re-stamps it on re-termination)."""
        with self._lock:
            spec = self._specs.get(tp.taskpool_id)
            if spec is not None:
                spec["completed_at"] = time.monotonic()

    def _sweep_locked(self) -> None:   # holds-lock: _lock
        """Evict specs (and the tile snapshots only they referenced) of
        pools that retired, were cancelled, or completed past the grace
        window — a resident service must not accumulate O(jobs served)
        pool objects and snapshot bytes, nor resurrect ancient jobs on
        a peer death.  Caller holds _lock."""
        now = time.monotonic()
        for tpid in list(self._specs):
            spec = self._specs[tpid]
            tp = spec["tp"]
            done_at = spec["completed_at"]
            stale = (getattr(tp, "retired", False) or tp.cancelled
                     or (done_at is not None
                         and now - done_at > self.completed_grace))
            if stale and tpid not in self._active:
                del self._specs[tpid]
                self._attempts.pop(tpid, None)
        live_dcs = {id(dc) for spec in self._specs.values()
                    for dc in spec["collections"]}
        for key in [k for k in self._snaps if k not in live_dcs]:
            self._snaps.pop(key, None)
            self._snap_dcs.pop(key, None)

    # -- containment hand-off (comm thread; must not block) --------------
    def on_peer_dead(self, rank: int, exc: Exception,
                     pools: List[Taskpool]):
        """Decide, per pool, recovery vs containment.  Returns
        ``(handled, leftover)``: ``handled`` True when this death is
        excused (the service degrades-but-survives even with zero
        affected pools); ``leftover`` are pools recovery will NOT take
        — the caller contains them as before."""
        ce = self._rde.ce if self._rde is not None else None
        if not self.enabled or ce is None \
                or getattr(ce, "fault_killed", False) \
                or rank == self.context.rank:
            return False, pools
        take: List[Taskpool] = []
        leave: List[Taskpool] = []
        touching = {tp.taskpool_id for tp in pools}
        with self._lock:
            # the restart set is GANG-WIDE per pool, not per-traffic:
            # the re-executed DAG is global, so every survivor must
            # restart a pool whose collections span the dead rank even
            # if ITS partition never exchanged a frame with it — a
            # survivor left on the old generation would park the new
            # generation's activations forever.  Registered pools whose
            # collections cannot contain the dead rank are genuinely
            # unaffected and stay untouched.
            candidates = list(pools)
            for spec in self._specs.values():
                tp = spec["tp"]
                # completed-but-not-RETIRED pools are candidates too:
                # local completion is not global completion, and a
                # survivor whose partition drained early must still
                # restart so the adopter's re-executed activations have
                # somewhere to land (retired = a quiescence round
                # proved the whole gang done; never resurrected)
                if tp.taskpool_id in touching \
                        or getattr(tp, "retired", False) \
                        or tp.cancelled or not spec["collections"]:
                    continue
                if tp.completed:
                    # locally complete: restartable only within the
                    # grace window — past it the gang has long since
                    # quiesced and a resident service's history must
                    # never be resurrected
                    done_at = spec["completed_at"]
                    if done_at is None or \
                            time.monotonic() - done_at \
                            > self.completed_grace:
                        continue
                if any(getattr(dc, "nodes", 1) > rank
                       for dc in spec["collections"]):
                    candidates.append(tp)
            for tp in candidates:
                spec = self._specs.get(tp.taskpool_id)
                # insert-driven pools (anything that is not a
                # parameterized enumeration) NEED a replay callable: a
                # base startup() re-enumerates nothing, and a restart
                # would restore the tiles, re-execute zero tasks, and
                # "complete" with silently reverted data
                replayable = spec is not None and (
                    spec["replay"] is not None
                    or isinstance(tp, ParameterizedTaskpool))
                ok = (spec is not None and spec["collections"]
                      and replayable
                      and not tp.cancelled
                      and not getattr(tp, "retired", False)
                      and not getattr(tp, "_compound_member", False)
                      and not getattr(tp, "_dyn_hold", False)
                      and hasattr(tp.termdet, "taskpool_reset")
                      and self._attempts.get(tp.taskpool_id, 0)
                      < self.max_attempts)
                if ok:
                    self._attempts[tp.taskpool_id] = \
                        self._attempts.get(tp.taskpool_id, 0) + 1
                    self._active.add(tp.taskpool_id)
                    take.append(tp)
                elif tp.taskpool_id in touching:
                    leave.append(tp)   # containment, exactly as before
            self._events.append((rank, exc, take))
            self._pending_dead.add(rank)
            worker = self._worker
            if worker is None or not worker.is_alive():
                worker = threading.Thread(target=self._run,
                                          name="parsec-recovery",
                                          daemon=True)
                self._worker = worker
                worker.start()
        # excuse SYNCHRONOUSLY, on the declaring thread: a survivor
        # polling wait_quiescence every 50 ms must never observe
        # dead-but-not-yet-excused in the window before the recovery
        # worker gets scheduled (the fatal check would fail a run the
        # recovery is about to save); _process_event's excusal is then
        # a harmless repeat
        ce.excuse_peer(rank)
        self.counts["started"] += 1
        self.context.telemetry_incident(
            f"recovery-start rank={rank} pools="
            f"{[tp.taskpool_id for tp in take]}")
        warning("rank %d: RECOVERY engaged for dead rank %d (%d pool(s) "
                "re-executing, %d contained)", self.context.rank, rank,
                len(take), len(leave))
        self._notify_services("start", rank)
        return True, leave

    def recovering(self, tp) -> bool:
        """Is a recovery restart pending/active for this pool?  The
        containment paths consult it to swallow secondary errors of the
        torn generation (dead-child sends, parked pulls) instead of
        failing a pool that is already being rebuilt."""
        with self._lock:
            return tp is not None and tp.taskpool_id in self._active

    def excused(self, rank: int) -> bool:
        with self._lock:
            return rank in self._dead_map or rank in self._pending_dead

    # -- the recovery thread ---------------------------------------------
    def _apply_untranslate(self) -> None:
        """Clear translation entries of ranks that rejoined while a
        restart was active, once the restart pipeline drained — a
        deferred clear nobody applies would leave the rejoined rank's
        partition re-mapped forever."""
        with self._lock:
            if self._active or self._events \
                    or not self._pending_untranslate:
                return
            pend = set(self._pending_untranslate)
            self._pending_untranslate.clear()
            translated = list(self._translated)
        for dc in translated:
            table = dict(dc._recovery_translate or {})
            for r in pend:
                table.pop(r, None)
            dc.set_rank_translation(table)

    def _run(self) -> None:
        while True:
            with self._lock:
                if not self._events:
                    # retire INSIDE the lock: on_peer_dead appends and
                    # checks worker liveness under the same lock, so an
                    # event can never strand between our empty-check
                    # and exit
                    self._worker = None
                    break
                rank, exc, pools = self._events.popleft()
            try:
                self._process_event(rank, exc, pools)
            except Exception as run_exc:   # the thread must drain events
                warning("rank %d: recovery event for rank %d failed: %s",
                        self.context.rank, rank, run_exc)
                self.counts["failed"] += 1
                with self._lock:
                    self._pending_dead.discard(rank)
                for tp in pools:
                    with self._lock:
                        self._active.discard(tp.taskpool_id)
                    self.context.record_pool_error(tp, exc)
                self._notify_services("failed", rank)
        self._apply_untranslate()

    def _process_event(self, rank: int, exc: Exception,
                       pools: List[Taskpool]) -> None:
        ctx, rde = self.context, self._rde
        ce = rde.ce
        t0 = time.monotonic()
        # 1. excuse + fence + Safra reconcile: from here, barriers and
        # quiescence run over the survivors, stale frames of the dead
        # incarnation are dropped before crediting, and the message
        # balance reflects live traffic only
        ce.excuse_peer(rank)
        rde.recovery_reconcile(rank)
        # the translation recomputes WHOLESALE from the dead SET (not
        # incrementally from event order): two survivors detecting two
        # near-simultaneous deaths in opposite order must still land on
        # the same table, and a chained adopter death (1->2, then 2
        # dies) must collapse onto a live rank
        dead_set = (set(ce.dead_peers) | {rank}) - {ce.rank}
        survivors = sorted(r for r in range(ce.nranks)
                           if r not in dead_set)
        if not survivors:
            raise RecoveryUnsupported("no survivors")
        with self._lock:
            self._dead_map = {d: survivors[d % len(survivors)]
                              for d in dead_set}
            target = self._dead_map[rank]
            self._pending_dead.discard(rank)
        debug_verbose(1, "rank %d: recovery re-maps rank %d -> %d "
                      "(survivors %s)", ctx.rank, rank, target, survivors)
        ok = True
        for tp in pools:
            try:
                n = self._restart_pool(tp, rank, target)
                self.tasks_reexecuted += n
                debug_verbose(1, "rank %d: pool %d re-executes %d "
                              "task(s)", ctx.rank, tp.taskpool_id, n)
            except Exception as restart_exc:
                ok = False
                warning("rank %d: recovery of pool %d failed (%s); "
                        "containing", ctx.rank, tp.taskpool_id,
                        restart_exc)
                with self._lock:
                    self._active.discard(tp.taskpool_id)
                ctx.record_pool_error(tp, exc)
        dt = time.monotonic() - t0
        self.duration_hist.observe(dt)
        self.counts["completed" if ok else "failed"] += 1
        self._notify_services("done" if ok else "failed", rank)
        warning("rank %d: recovery for dead rank %d %s in %.2fs",
                ctx.rank, rank, "completed" if ok else "FAILED", dt)

    def _restart_pool(self, tp: Taskpool, dead: int, target: int) -> int:
        """Rewind + restore + re-execute one pool.  Returns the local
        re-execution task count."""
        from parsec_tpu.core import scheduling
        ctx, rde = self.context, self._rde
        with self._lock:
            spec = self._specs[tp.taskpool_id]
        if getattr(tp, "retired", False):
            # globally done (a quiescence round proved the whole gang
            # finished): nothing left to re-execute anywhere
            with self._lock:
                self._active.discard(tp.taskpool_id)
            return 0
        # partition re-mapping on THIS pool's collections (plus the
        # pool-level table DTD integer affinities consult).  The
        # pre-restore window is TRANSACTIONAL: a failed pre-flight must
        # roll the tables back, or owner_of would keep routing the dead
        # partition here with no restored payloads — a later pool over
        # the same collection would then materialize zero-filled
        # adopted tiles and silently compute garbage
        with self._lock:
            dead_map = dict(self._dead_map)
        prev_tables = [(dc, dict(dc._recovery_translate)
                        if dc._recovery_translate else None)
                       for dc in spec["collections"]]
        for dc in spec["collections"]:
            # the FULL normalized map, not just this event's entry: a
            # chained adopter death re-targets earlier entries too
            table = dict(dc._recovery_translate or {})
            table.update(dead_map)
            dc.set_rank_translation(table)
            with self._lock:
                if dc not in self._translated:
                    self._translated.append(dc)
        tp.rank_translation = dead_map
        try:
            # pre-flight: every tile this rank now owns must have a
            # restore source — check BEFORE tearing runtime state down
            plan = self._restore_plan(spec)
            # park inbound activations (state < RUNNING), then fence
            # stale generations (run_epoch) and wait their bodies out
            tp.state = TaskpoolState.ATTACHED
            tp.run_epoch += 1
            # belt only: correctness rides on claim-before-fence-check
            # in task_progress (the drain observes every claimed body);
            # this just skips one drain poll for tasks popped right at
            # the bump
            time.sleep(0.02)
            self._drain_inflight(tp)
            try:
                ctx.sync_devices(timeout=5.0)
            except Exception as exc:
                debug_verbose(2, "recovery device sync: %s", exc)
            # comm: drop the torn generation's parked/queued state
            rde.forget_pool(tp)
            # termdet rewind.  force_terminated: a pool that completed
            # LOCALLY (its partition drained before the kill) must
            # still restart — the adopter's re-executed activations
            # land here — and the returned TERMINATED tells us to
            # re-arm the completion bookkeeping its termination already
            # released
            was = tp.termdet.taskpool_reset(tp, force_terminated=True)
            if was is None:
                tp.state = TaskpoolState.DONE
                with self._lock:
                    self._active.discard(tp.taskpool_id)
                return 0
            from parsec_tpu.core.termdet import TermdetState
            if was == TermdetState.TERMINATED:
                with ctx._lock:
                    ctx._active_taskpools += 1
                tp._done_event.clear()
            tp.termdet.taskpool_addto_runtime_actions(tp, 1)  # startup
            tp.recovery_reset()
            # restore the last surviving version of every owned tile
            for dc, idx, arr in plan:
                dc.data_of(*idx).overwrite_host(np.asarray(arr))
        except Exception:
            # anything failing BEFORE the restore finished leaves the
            # adopted partition unrestored: roll the translation back
            # so no later pool sees zero-filled adopted tiles as local
            # (the pool itself is contained by the caller)
            for dc, prev in prev_tables:
                dc.set_rank_translation(prev)
            raise
        # re-insert the re-execution sub-DAG
        if spec["replay"] is not None:
            spec["replay"](tp)
            n = max(int(tp.nb_tasks), 0)
        else:
            ready = tp.startup()
            n = max(int(tp.nb_tasks), 0)
            if ready:
                scheduling.schedule(ctx.streams[0], ready)
        tp.ready()
        with self._lock:
            self._active.discard(tp.taskpool_id)
        # frames parked while the pool was down deliver into the new
        # generation now
        rde.retry_delayed()
        drain = getattr(ctx.comm, "dtd_drain_backlog", None)
        if drain is not None and hasattr(tp, "_dtd_incoming"):
            drain(tp)
        return n

    def _restore_plan(self, spec) -> List[Tuple[Any, Tuple, Any]]:
        """(dc, idx, payload) for every tile this rank serves after the
        re-mapping; raises RecoveryUnsupported when a tile has neither a
        snapshot nor a re-runnable source."""
        plan: List[Tuple[Any, Tuple, Any]] = []
        for dc in spec["collections"]:
            if not hasattr(dc, "local_tiles"):
                raise RecoveryUnsupported(
                    f"collection {dc.name!r} has no local_tiles "
                    "enumeration")
            with self._lock:
                snap = dict(self._snaps.get(id(dc), ()))
            for idx in dc.local_tiles():
                idx = tuple(idx) if isinstance(idx, (tuple, list)) \
                    else (idx,)
                if idx in snap:
                    plan.append((dc, idx, snap[idx]))
                elif dc.init_fn is not None:
                    plan.append((dc, idx, dc.init_fn(*idx)))
                else:
                    raise RecoveryUnsupported(
                        f"{dc.name}{idx}: no surviving snapshot and no "
                        "init_fn re-runnable source (set one with "
                        "collection.set_init)")
        return plan

    def _drain_inflight(self, tp: Taskpool) -> None:
        """Wait (bounded) until no worker stream is still executing a
        stale-generation body of this pool: their in-place tile writes
        must land BEFORE the restore overwrites them, never after.  A
        drain that cannot complete ABORTS the recovery (the caller
        contains the pool): restoring under a still-running stale body
        would be silent corruption, strictly worse than the contained
        failure recovery replaces."""
        deadline = time.monotonic() + self.drain_s
        while time.monotonic() < deadline:
            busy = False
            for es in self.context.streams:
                t = es.running_task
                if t is not None and t.taskpool is tp \
                        and t.pool_epoch != tp.run_epoch:
                    busy = True
                    break
            if not busy:
                return
            time.sleep(0.005)
        raise RecoveryUnsupported(
            f"rank {self.context.rank}: stale-generation bodies of "
            f"pool {tp.taskpool_id} still running after "
            f"{self.drain_s:g}s drain — restoring under them would "
            "corrupt the lineage base")

    # -- rejoin ----------------------------------------------------------
    def on_rejoin_request(self, src: int, msg: dict) -> Optional[dict]:
        """Survivor side of the rejoin handshake (comm thread): validate
        the incarnation epoch against the fence, clear the dead mark,
        hand back the translation table.  Returns the ack payload, or
        None to deny."""
        rde = self._rde
        if rde is None:
            return None
        epoch = int(msg.get("epoch", 0))
        fence = rde.peer_fence(src)
        if epoch < fence:
            warning("rank %d: rejected rejoin of rank %d with stale "
                    "epoch %d (fence %d)", self.context.rank, src,
                    epoch, fence)
            return None
        rde.note_peer_epoch(src, epoch)
        rde.ce.peer_rejoined(src, epoch)
        busy = False
        with self._lock:
            self._peer_epochs[src] = epoch
            self._dead_map.pop(src, None)
            dead_map = dict(self._dead_map)
            busy = bool(self._active) or bool(self._events)
            translated = list(self._translated)
            if busy:
                # a restart mid-flight keeps its table until done (the
                # re-executing tasks must keep resolving to their
                # adopter); the recovery thread applies the clear once
                # the pipeline drains (_apply_untranslate)
                self._pending_untranslate.add(src)
        if not busy:
            # the rank takes its partition back for FUTURE lookups
            for dc in translated:
                table = dict(dc._recovery_translate or {})
                table.pop(src, None)
                dc.set_rank_translation(table)
        self.rejoins += 1
        self._notify_services("rejoin", src)
        warning("rank %d: rank %d REJOINED (incarnation epoch %d)",
                self.context.rank, src, epoch)
        ce = rde.ce
        with ce._bar_cond:
            bar_gen = ce._bar_gen
        return {"k": "ack", "epoch": epoch, "rank": self.context.rank,
                "translation": dead_map, "bar_gen": bar_gen}

    def rejoin(self, timeout: float = 30.0) -> Dict[int, int]:
        """Restarted-rank side: announce the new incarnation to every
        live peer and wait for the first ack; returns the received
        translation table (other still-dead ranks' re-mappings)."""
        rde = self._rde
        if rde is None:
            raise RuntimeError("rejoin needs an attached comm engine")
        ce = rde.ce
        peers = [r for r in range(ce.nranks)
                 if r != ce.rank and r not in ce.dead_peers]
        if not peers:
            raise RuntimeError("rejoin: no live peers to rejoin")
        req = {"k": "req", "rank": ce.rank, "epoch": ce.epoch}
        for r in peers:
            from parsec_tpu.comm.engine import TAG_REJOIN
            ce.send_am(TAG_REJOIN, r, dict(req))
        ack = ce.wait_rejoin_ack(timeout)
        if ack is None:
            raise TimeoutError(
                f"rank {ce.rank}: rejoin not acknowledged within "
                f"{timeout:g}s (every survivor denied the epoch or was "
                "unreachable)")
        table = {int(k): int(v)
                 for k, v in (ack.get("translation") or {}).items()}
        with self._lock:
            self._dead_map.update(table)
        # generation-numbered state transfer: the fresh engine's barrier
        # counter syncs to the survivors' so the next collective round
        # numbers match across the rebuilt gang
        with ce._bar_cond:
            ce._bar_gen = max(ce._bar_gen,
                              int(ack.get("bar_gen", 0)))
        return table

    # -- observability ----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                **self.counts,
                "tasks_reexecuted": self.tasks_reexecuted,
                "rejoins": self.rejoins,
                "dead_map": dict(self._dead_map),
                "active_pools": sorted(self._active),
            }

    def _collect(self) -> List[dict]:
        """Scrape-time metrics families (prof/metrics.py collector —
        zero hot-path hooks; every value accumulates on the recovery/
        comm threads and is read here)."""
        from parsec_tpu.prof.metrics import (counter_sample,
                                             histogram_sample)
        out = [counter_sample("parsec_recoveries_total", v,
                              {"stage": stage})
               for stage, v in self.counts.items()]
        out.append(counter_sample("parsec_tasks_reexecuted_total",
                                  self.tasks_reexecuted))
        out.append(counter_sample("parsec_rank_rejoins_total",
                                  self.rejoins))
        out.append(histogram_sample("parsec_recovery_duration_seconds",
                                    self.duration_hist))
        return out
