"""Virtual-process map and thread placement.

Rebuild of the reference's vpmap + hwloc binding pair (reference:
parsec/vpmap.{c,h} — #VPs, threads per VP, core affinities, initialized
from flat/parameters/hardware — and parsec_hwloc.c/bindthread.c thread->
core binding).  A virtual process (VP) groups execution streams that
share a scheduler domain (per-VP queues in llp/ap, NUMA islands in the
reference); on this platform topology discovery is os-level (no hwloc):
``from_hardware`` splits the streams across the machine's cores, and
binding uses ``os.sched_setaffinity`` where the OS provides it.

MCA: ``--mca vpmap flat`` (default, one VP), ``--mca vpmap 2:4`` (2 VPs
x 4 streams), ``--mca vpmap hw``; ``--mca runtime_bind_threads 1`` pins
each worker to a core round-robin.
"""

from __future__ import annotations

import os
from typing import List, Optional

from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose, warning

params.register("vpmap", "flat",
                "virtual-process map: flat | <nvp>:<threads_per_vp> | hw | file:<path>")
params.register("runtime_bind_threads", 0,
                "bind worker threads to cores round-robin (Linux only)")


def _parse_cpu_list(s: str) -> List[int]:
    """Kernel cpu-list syntax: ``0-3,8,10-11`` -> [0,1,2,3,8,10,11]."""
    out: List[int] = []
    for tok in s.strip().split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "-" in tok:
            lo, _, hi = tok.partition("-")
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(tok))
    return out


def discover_topology(sysfs_root: str = "/sys"):
    """OS-level hardware topology (the hwloc counterpart — reference:
    parsec_hwloc.c builds the socket/cache hierarchy; here the kernel's
    sysfs exports the same facts): reads
    ``cpu*/topology/package_cpus_list`` and
    ``cpu*/cache/index*/{level,type,shared_cpu_list}`` into grouped
    core lists per sharing level.

    Returns ``{"cpus": [ids...], "package": [[cores]...],
    "l3": [...], "l2": [...], "l1": [...]}`` where each level lists
    disjoint groups of cores sharing that resource.  Missing sysfs
    (non-Linux, containers) yields single/empty groups — callers fall
    back to flat splits."""
    base = os.path.join(sysfs_root, "devices/system/cpu")
    cpus: List[int] = []
    try:
        for name in os.listdir(base):
            m = name.startswith("cpu") and name[3:].isdigit()
            if m:
                cpus.append(int(name[3:]))
    except OSError:
        return {"cpus": [], "package": [], "l3": [], "l2": [], "l1": []}
    cpus.sort()

    def read(path: str) -> Optional[str]:
        try:
            with open(path) as fh:
                return fh.read().strip()
        except OSError:
            return None

    def groups_from(keyfn) -> List[List[int]]:
        seen = {}
        for c in cpus:
            key = keyfn(c)
            if key is None:
                key = ("self", c)
            seen.setdefault(key, []).append(c)
        return sorted(seen.values(), key=lambda g: g[0])

    def pkg_key(c: int):
        s = read(f"{base}/cpu{c}/topology/package_cpus_list")
        return tuple(_parse_cpu_list(s)) if s else None

    def cache_key(level: int):
        def key(c: int):
            cdir = f"{base}/cpu{c}/cache"
            try:
                idxs = [n for n in os.listdir(cdir)
                        if n.startswith("index")]
            except OSError:
                return None
            for idx in idxs:
                lv = read(f"{cdir}/{idx}/level")
                ty = read(f"{cdir}/{idx}/type") or ""
                if lv and int(lv) == level and ty != "Instruction":
                    s = read(f"{cdir}/{idx}/shared_cpu_list")
                    if s:
                        return tuple(_parse_cpu_list(s))
            return None
        return key

    return {
        "cpus": cpus,
        "package": groups_from(pkg_key),
        "l3": groups_from(cache_key(3)),
        "l2": groups_from(cache_key(2)),
        "l1": groups_from(cache_key(1)),
    }


class VPMap:
    """Stream -> (vp, core) placement (reference: vpmap.h:45-68)."""

    def __init__(self, nb_threads: int, vp_of: List[int],
                 core_of: Optional[List[Optional[int]]] = None):
        self.nb_threads = nb_threads
        self._vp_of = vp_of
        self._core_of = core_of or [None] * nb_threads
        self.nb_vps = (max(vp_of) + 1) if vp_of else 1

    # -- constructors (reference: vpmap_init_from_*) ----------------------
    @classmethod
    def from_flat(cls, nb_threads: int) -> "VPMap":
        """One VP holding every stream (reference: vpmap_init_from_flat)."""
        return cls(nb_threads, [0] * nb_threads)

    @classmethod
    def from_parameters(cls, spec: str, nb_threads: int) -> "VPMap":
        """``<nvp>:<threads_per_vp>`` (reference:
        vpmap_init_from_parameters)."""
        try:
            nvp_s, tpv_s = spec.split(":")
            nvp, tpv = max(1, int(nvp_s)), max(1, int(tpv_s))
        except ValueError:
            warning("vpmap %r unparseable; falling back to flat", spec)
            return cls.from_flat(nb_threads)
        return cls(nb_threads, [min(i // tpv, nvp - 1)
                                for i in range(nb_threads)])

    @classmethod
    def from_hardware(cls, nb_threads: int,
                      sysfs_root: str = "/sys") -> "VPMap":
        """One VP per hardware locality domain (reference:
        vpmap_init_from_hardware_affinity, parsec_hwloc.c socket/NUMA
        grouping): ``discover_topology`` reads the kernel's cache +
        package hierarchy and the VP groups follow the deepest level
        with real sharing — packages, else shared-LLC islands.  With no
        discoverable structure (1 core, no sysfs) this degenerates to
        contiguous balanced core blocks, the old behavior."""
        topo = discover_topology(sysfs_root)
        groups: List[List[int]] = []
        for lvl in ("package", "l3", "l2"):
            lv = topo.get(lvl) or []
            # a level only structures the machine if it has SEVERAL
            # groups of genuinely shared cores (singleton-per-core
            # levels are no locality signal)
            if len(lv) > 1 and any(len(g) > 1 for g in lv):
                groups = lv
                break
        if len(groups) <= 1:
            ncores = len(topo.get("cpus") or []) or os.cpu_count() or 1
            nvp = max(1, min(nb_threads, ncores))
            return cls(nb_threads,
                       [i * nvp // nb_threads for i in range(nb_threads)],
                       [i % ncores for i in range(nb_threads)])
        # interleave streams across the domains (balanced VPs), binding
        # each to a concrete core of its domain
        order = []
        width = max(len(g) for g in groups)
        for j in range(width):
            for g, cores in enumerate(groups):
                if j < len(cores):
                    order.append((g, cores[j]))
        vp_of, core_of = [], []
        for i in range(nb_threads):
            g, c = order[i % len(order)]
            vp_of.append(g)
            core_of.append(c)
        return cls(nb_threads, vp_of, core_of)

    @classmethod
    def from_file(cls, path: str, nb_threads: int,
                  rank: int = 0) -> "VPMap":
        """Reference vpmap file format (reference: vpmap_init_from_file,
        parsec/vpmap.c:219): one VP per line, ``rank:nbthreads:binding``
        — a leading ':' (no rank) applies to every rank; ``binding`` is
        a comma list of cores with ``a-b`` ranges.  Lines for other
        ranks are skipped.  If the file describes a different thread
        count than ``nb_threads``, the map is clipped/extended
        round-robin with a warning (the reference spawns exactly the
        file's threads; here the context owns the stream count)."""
        vp_of: List[int] = []
        core_of: List[Optional[int]] = []
        try:
            with open(path) as fh:
                lines = fh.readlines()
        except OSError as exc:
            warning("vpmap file %s: %s; falling back to flat", path, exc)
            return cls.from_flat(nb_threads)
        vp = 0
        for line in lines:
            line = line.strip()
            if not line or line.startswith("#") or ":" not in line:
                if line and not line.startswith("#"):
                    warning("vpmap %s: malformed line %r", path, line)
                continue
            rank_s, _, rest = line.partition(":")
            try:
                if rank_s.strip() and int(rank_s) != rank:
                    continue
            except ValueError:
                warning("vpmap %s: malformed line %r", path, line)
                continue
            nbth_s, _, binding = rest.partition(":")
            try:
                nbth = max(1, int(nbth_s))
            except ValueError:
                warning("vpmap %s: malformed line %r", path, line)
                continue
            cores: List[Optional[int]] = []
            try:
                for tok in binding.split(","):
                    tok = tok.strip()
                    if not tok:
                        continue
                    if "-" in tok:
                        lo, _, hi = tok.partition("-")
                        cores.extend(range(int(lo), int(hi) + 1))
                    else:
                        cores.append(int(tok))
            except ValueError:
                warning("vpmap %s: malformed binding %r", path, line)
                cores = []
            for t in range(nbth):
                vp_of.append(vp)
                core_of.append(cores[t % len(cores)] if cores else None)
            vp += 1
        if not vp_of:
            warning("vpmap %s: no VP lines for rank %d; flat map", path,
                    rank)
            return cls.from_flat(nb_threads)
        if len(vp_of) != nb_threads:
            warning("vpmap %s describes %d threads, context runs %d; "
                    "mapping round-robin", path, len(vp_of), nb_threads)
            vp_of = [vp_of[i % len(vp_of)] for i in range(nb_threads)]
            core_of = [core_of[i % len(core_of)]
                       for i in range(nb_threads)]
        return cls(nb_threads, vp_of, core_of)

    @classmethod
    def from_mca(cls, nb_threads: int, rank: int = 0) -> "VPMap":
        spec = str(params.get("vpmap", "flat"))
        if spec == "hw":
            return cls.from_hardware(nb_threads)
        if spec.startswith("file:"):
            return cls.from_file(spec[5:], nb_threads, rank)
        if ":" in spec:
            return cls.from_parameters(spec, nb_threads)
        return cls.from_flat(nb_threads)

    # -- queries (reference: vpmap_get_*) ----------------------------------
    def vp_of(self, th_id: int) -> int:
        return self._vp_of[th_id] if th_id < len(self._vp_of) else 0

    def core_of(self, th_id: int) -> Optional[int]:
        return self._core_of[th_id] if th_id < len(self._core_of) else None

    def threads_of_vp(self, vp: int) -> List[int]:
        return [i for i, v in enumerate(self._vp_of) if v == vp]


def bind_current_thread(core: Optional[int]) -> bool:
    """Pin the calling thread to ``core`` (reference: parsec_bindthread).
    Returns True on success; silently no-ops where unsupported."""
    if core is None or not hasattr(os, "sched_setaffinity"):
        return False
    try:
        os.sched_setaffinity(0, {core})
        debug_verbose(7, "bound thread to core %d", core)
        return True
    except OSError:
        return False
