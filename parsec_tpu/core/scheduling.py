"""Task lifecycle progression and the worker loop.

Rebuild of the reference's scheduling core (reference: parsec/scheduling.c):
``task_progress`` is __parsec_task_progress:472 (prepare_input -> execute ->
complete), ``execute`` iterates incarnations like __parsec_execute:124, and
``worker_loop`` is the hot loop of __parsec_context_wait:537-676 with
exponential backoff on scheduler misses.  ``schedule`` is __parsec_schedule,
entering tasks through the pluggable scheduler and ringing the doorbell.
"""

from __future__ import annotations

import time
from threading import get_ident
from typing import List, Optional

from parsec_tpu.core import engine
from parsec_tpu.core.errors import FaultInjected, TaskRetryExhausted
from parsec_tpu.core.task import HookReturn, Task, TaskStatus
from parsec_tpu.data.data import ACCESS_WRITE
from parsec_tpu.utils import faultinject as _fi
from parsec_tpu.utils.output import debug_verbose, warning


#: hoisted enum constants: a class-attribute load per task adds up at
#: 100k+ tasks/s (the native hot-path PR's bytecode diet)
_READY = TaskStatus.READY
_PREPARED = TaskStatus.PREPARED
_RUNNING = TaskStatus.RUNNING
_COMPLETE = TaskStatus.COMPLETE
_DONE = HookReturn.DONE
_ASYNC = HookReturn.ASYNC
_AGAIN = HookReturn.AGAIN
_NEXT = HookReturn.NEXT
_DISABLE = HookReturn.DISABLE


def schedule(es, tasks: List[Task], distance: int = 0) -> None:
    """Enter ready tasks into the scheduler (reference: __parsec_schedule)."""
    if not tasks:
        return
    ctx = es.context
    sched = ctx.scheduler
    if sched.NATIVE_BATCH:
        # native ready queue (sched/native.py): READY transition,
        # ready_at stamping, and the priority-ordered insert all ride
        # ONE C crossing for the whole ring
        sched.schedule(es, tasks, distance)
        ctx.ring_doorbell(len(tasks))
        return
    if ctx._ready_stamp:
        # one stamp for the batch: the tasks became ready at this same
        # moment; the causal tracer closes select - ready_at into a
        # queue-wait span and the metrics registry samples it into the
        # queue-wait histogram.  Gated (Context._ready_stamp) so a
        # telemetry-disabled hot path stays free
        now = time.perf_counter()
        for t in tasks:
            t.status = _READY
            t.ready_at = now
    else:
        for t in tasks:
            t.status = _READY
    sched.schedule(es, tasks, distance)
    ctx.ring_doorbell(len(tasks))


def execute(es, task: Task) -> HookReturn:
    """Iterate incarnations by preference until one takes the task
    (reference: __parsec_execute chore loop, scheduling.c:138-198)."""
    tc = task.task_class
    host_staged = False
    # no list() copy: NEXT/DISABLE mutate masks, never the list itself
    for idx, (dev_type, hook) in enumerate(tc.incarnations):
        if not (task.chore_mask & (1 << idx)):
            continue
        if tc.chore_disabled_mask & (1 << idx):
            continue
        if dev_type == "cpu" and not host_staged:
            engine.stage_in_host(task)
            host_staged = True
        ret = hook(es, task)
        if not isinstance(ret, HookReturn):
            # bodies opt into lifecycle control by returning HookReturn/int;
            # any other return value (arrays, bools, None...) means DONE
            ret = (HookReturn(ret)
                   if isinstance(ret, int) and not isinstance(ret, bool)
                   else _DONE)
        if ret == _NEXT:
            task.chore_mask &= ~(1 << idx)
            continue
        if ret == _DISABLE:
            # disable class-wide without mutating the list (indices — and
            # other tasks' chore masks — stay stable)
            tc.chore_disabled_mask |= 1 << idx
            continue
        return ret
    warning("%s: no incarnation accepted the task", task)
    return HookReturn.ERROR


def task_progress(es, task: Task, distance: int = 0) -> None:
    """Run one task through its lifecycle
    (reference: __parsec_task_progress)."""
    tp = task.taskpool
    # claim BEFORE the fence check: the recovery drain polls
    # running_task, and a worker descheduled between reading run_epoch
    # and publishing its claim would execute a stale body over
    # already-restored tiles — claimed-then-checked, the drain either
    # sees the claim and waits, or the check runs after the bump and
    # discards (the restore happens strictly after the bump)
    es.running_task = task
    try:
        if task.pool_epoch != tp.run_epoch:
            # recovery fence: the pool restarted (core/recovery.py)
            # after this task was scheduled.  Discard WITHOUT executing
            # and WITHOUT decrementing — the restart re-counted
            # nb_tasks from scratch and this instance belongs to the
            # torn generation (its repo/input holds died with the old
            # structures too)
            task.status = _COMPLETE
            es.pins("task_discard", task)
            return
        if tp.cancelled:
            # cancelled pool (job-service cancellation/deadline): drop
            # the task without executing or releasing successors; the
            # termdet was force-quiesced, so this decrement clamps at
            # zero.  The ready task holds predecessor repo entries
            # (input_sources, filled at dep delivery) — release them or
            # the warm context leaks the cancelled frontier's arena
            # tiles
            task.status = _COMPLETE
            es.pins("task_discard", task)
            try:
                engine.consume_inputs(task)
            except Exception as exc:
                debug_verbose(2, "discard %s: consume_inputs: %s",
                              task, exc)
            # lint: ignore[PCL-HOT] cancelled-pool discard: cold path
            tp.termdet.taskpool_addto_nb_tasks(tp, -1)
            return
        cbs = es._pins_map.get("exec_begin")   # inlined es.pins (hot path)
        if cbs:
            for cb in cbs:
                cb(es, "exec_begin", task)
        try:
            if task.status < _PREPARED:
                engine.prepare_input(es, task)
                task.status = _PREPARED
            if es.context._retry_max > 0 and task.retries == 0:
                _snapshot_write_flows(task)
            if _fi.ARMED:
                # fault plan hooks (utils/faultinject.py): keyed
                # delay_dispatch stalls a matching body (deterministic
                # straggler injection); fail_task raises a transient,
                # retryable failure
                _fi.task_delay(task)
                if _fi.task_fault(task):
                    raise FaultInjected(f"{task}: injected transient "
                                        "fault")
            task.status = _RUNNING
            ret = execute(es, task)
        except Exception as exc:  # body/binding error: retry or fail pool
            if _maybe_retry(es, task, exc, distance):
                return
            if task.retries:
                exc = TaskRetryExhausted(
                    f"{task}: still failing after {task.retries + 1} "
                    "attempts", attempts=task.retries + 1, last=exc)
            es.context.record_error(exc, task)
            complete_execution(es, task, failed=True)
            return
        if ret == _DONE:
            cbs = es._pins_map.get("exec_end")   # inlined es.pins
            if cbs:
                for cb in cbs:
                    cb(es, "exec_end", task)
            complete_execution(es, task)
        elif ret == _ASYNC:
            # device module owns the task; it calls complete_execution
            es.pins("exec_async", task)
        elif ret == _AGAIN:
            task.status = _READY
            schedule(es, [task], distance + 1)
        else:
            es.context.record_error(
                RuntimeError(f"{task} failed with {ret!r}"), task)
            complete_execution(es, task, failed=True)
    finally:
        es.running_task = None


def _snapshot_write_flows(task: Task) -> None:
    """Transient-retry support: snapshot host write-flow payloads before
    the first execution attempt, so a retried body re-runs against the
    ORIGINAL inputs even if the failed attempt mutated them in place
    (read-only and task-fed versioned inputs are already safe — the
    datarepo pins their version).  Only armed when task_retry_max > 0."""
    import numpy as np
    snap = {}
    for flow in task.task_class.flows:
        if not flow.access & ACCESS_WRITE:
            continue
        copy = task.data.get(flow.name)
        p = copy.payload if copy is not None else None
        if isinstance(p, np.ndarray):
            snap[flow.name] = p.copy()
    task.retry_snap = snap


def _maybe_retry(es, task: Task, exc: Exception, distance: int) -> bool:
    """Transient-failure retry: reschedule an idempotent task whose body
    raised, up to ``task_retry_max`` attempts.  Device-owned (ASYNC)
    tasks are not retried here — the device layer has its own degrade
    path."""
    limit = es.context._retry_max
    if limit <= 0 or task.retries >= limit or task.taskpool.cancelled:
        return False
    if not task.task_class.properties.get("idempotent", True):
        return False
    import numpy as np
    snap = task.retry_snap
    for fname, arr in (snap or {}).items():
        copy = task.data.get(fname)
        if copy is not None:
            copy.payload = arr.copy()
    task.retries += 1
    task.status = TaskStatus.READY
    warning("%s: transient failure (%s: %s); retrying %d/%d", task,
            type(exc).__name__, exc, task.retries, limit)
    es.pins("task_retry", task)
    schedule(es, [task], distance + 1)
    return True


def complete_execution(es, task: Task, failed: bool = False) -> None:
    """Completion: version bumps, release deps, repo holds, termdet
    (reference: __parsec_complete_execution:441)."""
    tc = task.task_class
    tp = task.taskpool
    if task.pool_epoch != tp.run_epoch:
        # recovery fence (async arm): a device completer or retry path
        # finishing a pre-restart task must neither release successors
        # into the rebuilt dep structures nor decrement the re-counted
        # termdet — the restart owns every count of the new generation.
        # Its BODY ran, though, and may have mutated write-flow tiles
        # in place: bump their version clocks so the payloads can
        # never masquerade as the (unmutated) recorded version — the
        # minimal-replay planner then sees an unrecorded writer and
        # takes the restore-point fallback instead of synthesizing
        # from silently-corrupted "live" bytes
        for flow in tc._write_flows:
            copy = task.data.get(flow.name)
            if copy is not None and copy.data is not None \
                    and copy.data.collection is not None:
                copy.data.complete_write(copy.device)
        if task.dtd is not None and tp._lineage is not None:
            # DTD twin of the version taint: a SUCCESSFUL body's
            # in-place tile writes are LANDED bytes — advance the
            # tiles' applied_ver so the skip-agreement landed map
            # cannot claim an older version over mutated payloads.  A
            # FAILED body's bytes are indeterminate (it may have
            # mutated partway): they match NO version, so the pool
            # votes full instead
            taint = getattr(tp, "dtd_taint_stale", None)
            if taint is not None:
                taint(task.dtd, failed=failed)
        task.status = _COMPLETE
        es.pins("task_discard", task)
        return
    # recovery lineage (core/recovery.py LineageLog; None = zero work):
    # read versions snap BEFORE the write-flow bump below — an RW flow's
    # bound copy still carries the version the body consumed
    lin = tp._lineage
    lin_reads = None if (lin is None or failed) \
        else lin.snap_reads(task)
    if not failed:
        try:
            for flow in tc._write_flows:
                copy = task.data.get(flow.name)
                if copy is not None and copy.data is not None:
                    copy.data.complete_write(copy.device)
            ready = engine.release_deps(es, task)
            if ready:
                schedule(es, ready)
        except Exception as exc:
            # a dep-expression or write-back error must fail the context,
            # not silently kill the worker thread
            es.context.record_error(exc, task)
    if task.input_sources:
        try:
            engine.consume_inputs(task)
        except Exception as exc:
            es.context.record_error(exc, task)
    if lin is not None and not failed:
        # record AFTER release_deps: write versions are final (the
        # writeback path may have superseded the bound copy) and
        # flush_activations already noted this task's remote dests
        lin.record(task, lin_reads)
    task.status = _COMPLETE
    cbs = es._pins_map.get("complete_exec")   # inlined es.pins
    if cbs:
        for cb in cbs:
            cb(es, "complete_exec", task)
    es.nb_tasks_done += 1
    # batched termdet: decrements accumulate per WORKER and flush at
    # batch boundaries/idle (worker_loop) instead of paying a
    # threading.Lock round-trip per task.  Only the stream's OWNING
    # worker thread may touch the accumulator — an ASYNC device
    # completer finishing a task on its own thread with a borrowed es
    # takes the locked path (no flush guarantee there, and the dict is
    # single-writer by contract)
    acc = es._td_acc
    if acc is not None and get_ident() == es._td_tid:
        ent = acc.get(tp)
        if ent is not None and ent[0] == task.pool_epoch:
            ent[1] += 1
        else:
            acc[tp] = [task.pool_epoch, 1]
    else:
        # lint: ignore[PCL-HOT] off-worker/batch=1 path: no accumulator
        tp.termdet.taskpool_addto_nb_tasks(tp, -1)


def _native_body_failed(es, task, exc, distance: int = 0) -> None:
    """C-chain twin of ``task_progress``'s except branch: the trivial
    hook raised (called from schedext's fast path so retry/containment
    semantics stay byte-identical to the Python chain)."""
    if _maybe_retry(es, task, exc, distance):
        return
    if task.retries:
        exc = TaskRetryExhausted(
            f"{task}: still failing after {task.retries + 1} "
            "attempts", attempts=task.retries + 1, last=exc)
    es.context.record_error(exc, task)
    complete_execution(es, task, failed=True)


def _native_hook_return(es, task, ret, distance: int = 0) -> None:
    """C-chain twin of ``execute``'s return normalization plus
    ``task_progress``'s dispatch, for a non-None return from a trivial
    single-incarnation hook (AGAIN/ASYNC/DISABLE and raw values)."""
    tc = task.task_class
    if not isinstance(ret, HookReturn):
        if isinstance(ret, int) and not isinstance(ret, bool):
            try:
                ret = HookReturn(ret)
            except ValueError as exc:
                # Python-chain parity: execute()'s HookReturn(ret) of
                # an invalid code raises under task_progress's try and
                # becomes a contained task failure — NOT an exception
                # out of the worker loop (which would kill the thread
                # and hang the run with zero recorded errors)
                _native_body_failed(es, task, exc, distance)
                return
        else:
            ret = _DONE
    if ret == _NEXT or ret == _DISABLE:
        # single-incarnation class: declining it leaves no taker
        if ret == _DISABLE:
            tc.chore_disabled_mask |= 1
        else:
            task.chore_mask &= ~1
        warning("%s: no incarnation accepted the task", task)
        ret = HookReturn.ERROR
    if ret == _DONE:
        cbs = es._pins_map.get("exec_end")   # inlined es.pins
        if cbs:
            for cb in cbs:
                cb(es, "exec_end", task)
        complete_execution(es, task)
    elif ret == _ASYNC:
        es.pins("exec_async", task)
    elif ret == _AGAIN:
        task.status = _READY
        schedule(es, [task], distance + 1)
    else:
        es.context.record_error(
            RuntimeError(f"{task} failed with {ret!r}"), task)
        complete_execution(es, task, failed=True)


def _td_flush(es) -> None:
    """Apply the worker's batched termdet decrements — the batch
    boundary (quantum end / idle / worker exit).  Each entry carries
    the generation it accumulated under; the termdet drops
    torn-generation deltas under its own lock (recovery rewind).

    RE-ENTRANT: a flushed decrement can fire a pool termination whose
    completion callback synchronously completes an ASYNC parent task
    on THIS thread (core/recursive.py `_done`), appending to the
    accumulator mid-flush — so the accumulator is snapshotted and
    cleared FIRST, and re-entrant appends land in the fresh dict for
    the next boundary (worker_loop's idle branch flushes whenever the
    accumulator is non-empty, so they cannot strand)."""
    acc = es._td_acc
    if not acc:
        return
    items = list(acc.items())
    acc.clear()
    for tp, ent in items:
        # the amortized lock round-trip the per-task path no longer pays
        tp.termdet.taskpool_addto_nb_tasks(  # lint: ignore[PCL-HOT]
            tp, -ent[1], epoch=ent[0])


def _spin_poll(probe, window_s: float,
               _perf=time.perf_counter, _sleep=time.sleep):
    """Worker-inlined poll: briefly re-poll the ready queue, yielding
    the GIL each round so the comm loop can park its deliveries —
    an activation landing inside the window is picked up at
    GIL-handoff latency instead of a condvar wakeup (the shm
    doorbell's waiting-flag discipline, generalized to the worker
    doorbell)."""
    end = _perf() + window_s
    while _perf() < end:
        t = probe()
        if t is not None:
            return t
        _sleep(0)   # lint: allow-blocking (GIL yield, not a wait)
    return None


def worker_loop(es) -> None:
    """Steady-state worker (reference: __parsec_context_wait hot loop).

    Native path: ``schedext.run_quantum`` runs pop + select-PINS + the
    whole trivial prepare/execute/complete chain for up to
    ``termdet_batch`` tasks in ONE GIL crossing; tasks the C chain
    cannot take pop out (select already fired) for ``task_progress``.
    Termdet decrements accumulate per worker and flush at quantum
    boundaries and idle moments instead of locking per task."""
    ctx = es.context
    sched = ctx.scheduler
    native = sched.NATIVE_BATCH
    # native hot path: pop straight off the C ready queue, skipping the
    # select() frame (one Python call per task at 100k+ tasks/s)
    pop = sched._q.pop if native else None
    quantum = q = None
    if native:
        from parsec_tpu.native import load_schedext
        se = load_schedext()
        if se is not None and hasattr(se, "run_quantum"):
            quantum, q = se.run_quantum, sched._q
    batch = ctx._termdet_batch
    es._td_tid = get_ident()
    es._td_acc = {} if batch > 1 else None
    probe = pop if pop is not None else (lambda: sched.select(es))
    pins_map = es._pins_map
    misses = 0
    done_since = 0
    n = 0
    while not ctx.finished:
        sel_fired = False
        if quantum is not None:
            n, task = quantum(es, q, batch)
            # the C quantum fires select before handing a task back
            sel_fired = task is not None
            if n:
                misses = 0
                done_since += n
                if done_since >= batch:
                    _td_flush(es)
                    done_since = 0
        else:
            task = probe()
        if task is None and quantum is not None and n:
            continue   # progressed this quantum; go straight back
        if task is None:
            # idle moment: flush batched termdet (termination needs the
            # final decrements) — unconditionally on a non-empty
            # accumulator: a flush-fired completion callback may have
            # re-entered complete_execution and deposited a decrement
            # AFTER the counting reset — then drain deferred wavefront
            # placements (comm/ici.py defer_place) and wait
            if es._td_acc:
                _td_flush(es)
                done_since = 0
            misses += 1
            ctx.flush_ici()
            # re-read per idle moment, not cached at loop start: a comm
            # engine attaching after workers spin up (fabric-carved
            # meshes attach lazily) re-probes the core count and flips
            # this on — the running workers must see it
            spin_s = ctx._db_spin_s
            if misses <= 2 and spin_s > 0 and ctx.comm is not None:
                # worker-inlined comm poll (comm_inline_poll): cover
                # the just-went-idle window before paying a condvar
                # round-trip — the rtt wakeup-latency lever
                task = _spin_poll(probe, spin_s)
            if task is None:
                # exponential backoff on miss (reference:
                # scheduling.c:596-635); the probe re-checks the queue
                # under the doorbell lock so a push racing the
                # waiting-flag cannot be lost
                task = ctx.doorbell_wait(
                    min(0.0002 * (1 << min(misses, 8)), 0.05), probe)
            if task is None:
                continue
        misses = 0
        # select fires exactly once per task: the C quantum already
        # fired it for tasks IT hands back; spin/doorbell tasks and
        # the whole Python path arrive unfired
        if not sel_fired:
            cbs = pins_map.get("select")   # inlined es.pins
            if cbs:
                for cb in cbs:
                    cb(es, "select", task)
        task_progress(es, task)
        done_since += 1
        if done_since >= batch:
            _td_flush(es)
            done_since = 0
    while es._td_acc:   # worker exit: drain re-entrant deposits too
        _td_flush(es)
    debug_verbose(9, "worker %d: %d tasks", es.th_id, es.nb_tasks_done)
