"""Execution context: worker streams, scheduler, taskpool lifecycle.

Rebuild of the reference's context tree (reference:
include/parsec/execution_stream.h: parsec_context_t -> parsec_vp_t ->
parsec_execution_stream_t; bring-up parsec.c:384-900): one Context per
process holds N worker threads (execution streams), the selected scheduler,
the device registry, and the set of active taskpools.  API mirrors
parsec_init / parsec_context_add_taskpool / _start / _test / _wait / _fini
(reference: parsec/runtime.h:170-323).

TPU notes: worker threads orchestrate host-side task progression; the
actual FLOPs run inside XLA executables dispatched by the device layer, so
a handful of streams saturate a chip — the default nb_cores is deliberately
small, not one-per-CPU-core.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from parsec_tpu.core import scheduling
from parsec_tpu.core.task import Task
from parsec_tpu.core.taskpool import Taskpool, TaskpoolState
from parsec_tpu.core import termdet as termdet_mod
from parsec_tpu.sched import create as create_scheduler
from parsec_tpu.utils.mca import components, params
from parsec_tpu.utils.output import debug_verbose, inform

params.register("runtime_num_cores", 4, "worker execution streams")
params.register("sched", "", "scheduler component selection")
params.register("termdet", "", "termination-detection component selection")
params.register("runtime_autopsy_s", 45.0,
                "soft deadline of Context.wait: when completion takes "
                "longer than this, a one-shot HANG AUTOPSY is logged — "
                "termdet counters, per-pool pending tasks, per-peer "
                "queue depths and last-frame ages, in-flight rendezvous "
                "handles — so a stuck run is diagnosable from its log "
                "(0 disables)")
params.register("task_retry_max", 0,
                "retry a transiently-failing idempotent task body up to "
                "this many times before failing its pool with "
                "TaskRetryExhausted (datarepo-versioned inputs plus a "
                "pre-execution write-flow snapshot make re-execution "
                "safe; 0 = off; read at Context construction)")
params.register("termdet_batch", 64,
                "per-worker termdet decrement batch: completion "
                "decrements accumulate on the worker and flush to the "
                "locked counter every N tasks and at every idle moment "
                "(also the native run_quantum size).  1 = the pre-r14 "
                "lock round-trip per task (the A/B knob); recovery "
                "rewinds drop torn-generation batches under the "
                "termdet lock, so the generation fence holds")
params.register("comm_inline_poll", 1,
                "idle workers briefly re-poll the ready queue (GIL-"
                "yield spin) before blocking on the doorbell when a "
                "comm engine is attached — an activation landing in "
                "the window is picked up at GIL-handoff latency "
                "instead of a condvar wakeup (the rtt queue-wait "
                "lever).  0 = always block immediately; 1 = auto "
                "(spin only when the host has a spare core — on 1 "
                "core the spin steals the GIL from the comm loop it "
                "waits on, measured +44% rtt); 2 = force on")
params.register("doorbell_coalesce_us", 150,
                "the worker-inlined poll window in microseconds (see "
                "comm_inline_poll), which is also the window within "
                "which producer doorbells coalesce: ring_doorbell "
                "skips the condvar lock entirely while no worker has "
                "raised its waiting flag — the shm doorbell's "
                "waiting-flag suppression generalized to the worker "
                "doorbell")
params.register("runtime_gc_freeze", 1,
                "freeze the already-imported object graph out of cyclic "
                "GC's full-collection scans at first Context bring-up "
                "(gc.freeze, the CPython production idiom): the jax/"
                "numpy import graph is ~80k tracked objects and full "
                "collections scanning it cost ~3.3us/task on the tasks "
                "probe (measured r11: 65ms over 2 gen2 passes per 20k "
                "tasks).  Once per process; cycles allocated BEFORE "
                "bring-up are never reclaimed afterwards (they are "
                "process-permanent imports in every supported "
                "deployment).  0 = leave the collector alone")

params.register("recovery_enable", 0,
                "peer-death RECOVERY: surviving ranks re-map a dead "
                "rank's data partition onto themselves and re-execute "
                "the lost lineage instead of failing the affected "
                "taskpools (core/recovery.py).  0 (default) keeps the "
                "containment-only failure lifecycle: a dead peer fails "
                "the pools that touch it and the service degrades")

_gc_frozen = False


def _freeze_import_graph() -> None:
    """One-shot (per process): reclaim pre-existing garbage, then move
    the surviving import-time object population into GC's permanent
    generation.  Later Contexts skip — their working sets must stay
    collectable, and re-freezing would permanently pin each prior
    context's residue."""
    global _gc_frozen
    if _gc_frozen:
        return
    _gc_frozen = True
    import gc
    gc.collect()
    gc.freeze()


class ExecutionStream:
    """One worker stream (reference: parsec_execution_stream_t)."""

    def __init__(self, context: "Context", th_id: int, vp_id: int = 0):
        self.context = context
        self.th_id = th_id
        self.vp_id = vp_id
        self.nb_tasks_done = 0
        self.sched_data: Any = None
        #: task whose body is currently executing on this stream, or
        #: None — recovery's in-flight drain polls it so tile restore
        #: never races a stale-generation body's in-place writes
        self.running_task = None
        #: per-worker batched termdet accumulator ({taskpool: [epoch,
        #: count]}) and its owning thread id — installed by worker_loop
        #: (None = unbatched); single-writer: only the owning worker
        #: thread mutates it, off-thread completers take the locked path
        self._td_acc = None
        self._td_tid = 0
        self._pins_cbs = {}
        #: the context's event->callbacks dict, aliased so the per-task
        #: dispatch reads one attribute (pins_register mutates the dict
        #: in place; the binding itself never changes)
        self._pins_map = context._pins

    def pins(self, event: str, task: Task) -> None:
        """PINS instrumentation point (reference: PARSEC_PINS macros);
        the profiling layer registers callbacks here."""
        cbs = self._pins_map.get(event)
        if cbs:
            for cb in cbs:
                cb(self, event, task)


class Context:
    """Process-wide runtime context (reference: parsec_context_t)."""

    def __init__(self, nb_cores: Optional[int] = None,
                 scheduler: Optional[str] = None,
                 rank: int = 0, nranks: int = 1,
                 argv: Optional[List[str]] = None):
        if argv is not None:
            params.parse_cmdline(argv)
        self.rank = rank
        self.nranks = nranks
        self.nb_cores = nb_cores if nb_cores is not None \
            else params.get("runtime_num_cores", 4)
        self.finished = False                 # guarded-by: _lock, _cond
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)   # same RLock
        self._active_taskpools = 0            # guarded-by: _lock, _cond
        self._pending_start: List[Taskpool] = []   # guarded-by: _lock, _cond
        #: taskpool_id -> taskpool; kept after completion so late remote
        #: messages (GET serving) still resolve (reference: taskpool
        #: registry hash, parsec_internal.h; guarded-by: _lock, _cond)
        self.taskpools: dict = {}
        self._errors: List[tuple] = []        # guarded-by: _lock, _cond
        self._pins = {}
        self.comm = None               # comm engine (distributed layer)
        self.grapher = None            # DOT grapher (prof layer)
        self._causal_tracer = None     # prof/causal.py CausalTracer
        self.metrics = None            # prof/metrics.py RuntimeMetrics
        self._flightrec = None         # prof/flightrec.py FlightRecorder
        # control-plane black box (prof/journal.py): every protocol
        # decision — recovery rounds, termdet rewinds, retirement
        # handshakes, rejoin fencing, barrier generations, job
        # lifecycle — lands in this bounded ring; no per-task emits
        from parsec_tpu.prof.journal import install_journal
        install_journal(self)
        #: schedule() stamps Task.ready_at only when a telemetry
        #: consumer wants it (causal tracer or metrics registry), and
        #: devices/xla.py fires device_dispatch/device_done PINS only
        #: when someone subscribed; both maintained by
        #: _recompute_ready_stamp on (un)install
        self._ready_stamp = False
        self._device_spans = False
        #: transient-task retry budget, cached off the worker hot path
        #: (core/scheduling.task_progress probes it per task)
        self._retry_max = int(params.get("task_retry_max", 0))
        #: worker-doorbell discipline (cached off the hot path):
        #: per-worker termdet batch, the inlined-poll window, and the
        #: waiting-flag counter ring_doorbell suppresses against
        self._termdet_batch = max(1, int(params.get("termdet_batch", 64)))
        self._recompute_db_spin()
        self._db_waiters = 0          # GIL-atomic int (plain reads)
        self._db_suppressed = 0       # doorbells coalesced away (stats)

        # device layer (reference: parsec_mca_device_init, parsec.c:823)
        from parsec_tpu.devices import init_devices
        self.device_registry = init_devices(self)
        self.devices = self.device_registry.devices

        # properties dictionary: runtime-queryable hierarchical key
        # space for live tooling (reference: parsec/dictionary.c; see
        # utils/properties.py)
        from parsec_tpu.utils.properties import (PropertySpace,
                                                 install_runtime_properties)
        self.properties = PropertySpace()
        install_runtime_properties(self)

        # ICI transport: multi-device payload edges ride XLA collectives
        # (reference: the second comm-engine module seam, SURVEY §5.8).
        # Import first: it registers comm_ici_enabled, so an env override
        # (PARSEC_MCA_COMM_ICI_ENABLED=0) coerces to int instead of
        # arriving as a truthy raw string.
        from parsec_tpu.comm.ici import IciEngine
        self.ici = None
        if int(params.get("comm_ici_enabled", 1)):
            ici = IciEngine(self.device_registry)
            if ici.ndev >= 2:
                self.ici = ici

        # full cyclic-GC collections scanning the static import graph
        # were 30% of the tasks probe; freeze it out once per process —
        # HERE, after the jax-importing layers (devices/ici) brought
        # the graph in, but BEFORE this context's own cyclic state
        # (streams<->context, scheduler, comm buffers) exists: a later
        # context must stay collectable after fini, and so must most
        # of the first one (the pinned residue is the device registry,
        # whose XLA backend handles are process-global anyway)
        if int(params.get("runtime_gc_freeze", 1)):
            _freeze_import_graph()

        # termination detection: pools default to the MCA-selected module
        # but may name their own via Taskpool.termdet_name (reference:
        # termdet installed per taskpool, scheduling.c:692-697; modules
        # local / user_trigger behind the §2.9 seam)
        sel_name, td_cls = components.select(
            "termdet", params.get("termdet", "") or None)
        self._termdet_cls = td_cls
        self._termdet = td_cls()
        self._termdets = {sel_name: self._termdet}

        self.scheduler = create_scheduler(
            scheduler or (params.get("sched", "") or None))
        self.scheduler.install(self)

        # VP map: streams -> virtual processes (+ optional core binding)
        # (reference: vpmap_init_* + thread binding, parsec.c:543-583,:861)
        from parsec_tpu.core.vpmap import VPMap
        self.vpmap = VPMap.from_mca(self.nb_cores, rank=self.rank)
        self.streams = [ExecutionStream(self, i,
                                        vp_id=self.vpmap.vp_of(i))
                        for i in range(self.nb_cores)]
        for es in self.streams:
            self.scheduler.flow_init(es)
        bind = bool(int(params.get("runtime_bind_threads", 0)))

        def run_worker(es):
            if bind:
                import os as _os
                from parsec_tpu.core.vpmap import bind_current_thread
                core = self.vpmap.core_of(es.th_id)
                if core is None:   # flat/parameter maps carry no cores:
                    # synthesize the documented round-robin placement
                    core = es.th_id % (_os.cpu_count() or 1)
                bind_current_thread(core)
            scheduling.worker_loop(es)

        self._threads = [
            threading.Thread(target=run_worker, args=(es,),
                             name=f"parsec-worker-{es.th_id}", daemon=True)
            for es in self.streams]
        for t in self._threads:
            t.start()

        # MCA-selected PINS instrumentation modules (reference:
        # pins_init + per-thread PINS THREAD_INIT, parsec.c bring-up)
        from parsec_tpu.prof.pins import install_selected
        self._pins_modules = install_selected(self)

        # telemetry plane: the always-on metrics registry (PAPI-SDE
        # counterpart grown into a scrapeable registry) and the
        # crash-dump flight recorder (armed via flightrec_enabled)
        if int(params.get("metrics_enabled", 1)):
            from parsec_tpu.prof.metrics import RuntimeMetrics
            RuntimeMetrics(rank=self.rank).install(self)
        if int(params.get("flightrec_enabled", 0)):
            from parsec_tpu.prof.flightrec import FlightRecorder
            FlightRecorder(self).install(self)
        # recovery plane (core/recovery.py): opt-in — when disabled
        # (the default) every peer-death path keeps the containment
        # behavior, byte for byte
        self.recovery = None
        if int(params.get("recovery_enable", 0)):
            from parsec_tpu.core.recovery import RecoveryCoordinator
            self.recovery = RecoveryCoordinator(self)
        self._recompute_ready_stamp()

        debug_verbose(3, "context up: %d streams, scheduler=%s",
                      self.nb_cores, self.scheduler.name)

    def _recompute_ready_stamp(self) -> None:
        """Telemetry-consumer gates: schedule() stamps Task.ready_at
        iff someone consumes it, and the device layer emits its
        dispatch/done span events iff someone registered for them."""
        self._ready_stamp = (self._causal_tracer is not None
                             or self.metrics is not None)
        fr = self._flightrec
        self._device_spans = (self._causal_tracer is not None
                              or (fr is not None
                                  and "device" in fr.classes))

    def _recompute_db_spin(self) -> None:
        """Arm (or re-arm) the inlined comm-poll window from the
        CURRENT core affinity.  The spin needs a spare core: on a
        1-core host a polling worker steals the GIL/CPU from the very
        comm loop whose delivery it is waiting for (measured: shm rtt
        694 -> 1000 us/hop with the spin forced on 1 core — BENCH.md
        r14); auto mode (1) arms it only with a spare core, 2 forces.

        Called from ``__init__`` AND whenever a comm engine attaches
        (comm/remote_dep.py): a fabric-carved worker is re-pinned
        after its Context was built, so the auto probe must read
        ``sched_getaffinity`` at attach time — an import-time or
        init-time reading of 1 core on a multi-core host would never
        arm the spare-core poll.  Workers pick the new window up on
        their next idle pass (worker_loop re-reads per wait)."""
        try:
            import os as _os
            ncores = len(_os.sched_getaffinity(0))
        except (AttributeError, OSError):
            import os as _os
            ncores = _os.cpu_count() or 1
        ip = int(params.get("comm_inline_poll", 1))
        self._db_spin_s = (
            max(0, int(params.get("doorbell_coalesce_us", 150))) * 1e-6
            if ip == 2 or (ip == 1 and ncores > 1) else 0.0)

    def telemetry_incident(self, reason: str):
        """Fire the flight recorder's incident dump (no-op unarmed).
        Called from containment/error paths — must never raise."""
        fr = self._flightrec
        if fr is None:
            return None
        try:
            return fr.incident(reason)
        except Exception as exc:
            debug_verbose(1, "flight recorder incident failed: %s", exc)
            return None

    # -- PINS registration -------------------------------------------------
    def pins_register(self, event: str, cb: Callable) -> None:
        self._pins.setdefault(event, []).append(cb)

    def pins_unregister(self, event: str, cb: Callable) -> None:
        if event in self._pins and cb in self._pins[event]:
            self._pins[event].remove(cb)

    def accelerator_spaces(self) -> list:
        """Memory-space indices of the enabled accelerators — the pool
        the serving fabric's mesh carver (service/fabric.py) allocates
        per-tenant device subsets from.  Space 0 (host) never appears:
        carving governs accelerator placement only."""
        return [d.space for d in self.device_registry.accelerators]

    def flush_ici(self) -> None:
        """Drain deferred wavefront placements (comm/ici.py defer_place)
        whose batching window expired.  Best-effort prefetch: failures
        must not kill the calling worker — consumers fall back to lazy
        stage-in."""
        if self.ici is None:
            return
        try:
            self.ici.flush_placements()
        except Exception as exc:
            from parsec_tpu.utils.output import debug_verbose
            debug_verbose(3, "flush_ici: %s", exc)

    # -- doorbell ----------------------------------------------------------
    def ring_doorbell(self, n: int = 1) -> None:
        """Wake up to ``n`` idle workers.  Coalesced: while no worker
        has raised its waiting flag (busy or inside the inlined poll
        window) the condvar lock is skipped entirely — the shm
        transport's consumer-side waiting-flag suppression, applied to
        the worker doorbell.  No lost wakeups: doorbell_wait raises
        the flag and re-probes the queue under the lock, so a push
        that raced the flag is observed by the probe."""
        if self._db_waiters:
            with self._cond:
                self._cond.notify(n)
        else:
            self._db_suppressed += 1

    def doorbell_wait(self, timeout: float, probe=None):
        """Park until a doorbell or ``timeout``.  ``probe`` (the ready
        queue's pop) re-checks for work under the lock AFTER the
        waiting flag went up: a producer that pushed before reading
        the flag is caught by the probe, one that read the flag after
        our raise takes the notify path — either way no lost wakeup.
        Returns the probed task, or None."""
        with self._cond:
            if self.finished:
                return None
            self._db_waiters += 1
            try:
                if probe is not None:
                    t = probe()
                    if t is not None:
                        return t
                self._cond.wait(timeout)
            finally:
                self._db_waiters -= 1
        return None

    # -- taskpool lifecycle ------------------------------------------------
    def add_taskpool(self, tp: Taskpool, start: bool = False) -> None:
        """reference: parsec_context_add_taskpool (scheduling.c:678)."""
        with self._lock:
            self._active_taskpools += 1
            # register BEFORE attach: attach may drain comm backlogs whose
            # re-delivery path looks the pool up in this table — a message
            # arriving in between must find it
            self.taskpools[tp.taskpool_id] = tp
            tp.attach(self, self.termdet_for(tp))
            self._pending_start.append(tp)
        from parsec_tpu.utils.properties import install_taskpool_properties
        install_taskpool_properties(self, tp)
        if self.recovery is not None:
            # recovery registration: snapshot the pool's collections'
            # local tiles (the lineage base a restart restores to) and
            # record its replay spec; pools without one stay on the
            # containment path
            self.recovery.register_pool(tp)
        if self.comm is not None:
            # activations may have raced this registration
            self.comm.retry_delayed()
        if start:
            self.start()

    def termdet_for(self, tp: Taskpool):
        """The termdet module instance for a pool: its named override or
        the context default (modules are shared per name)."""
        name = getattr(tp, "termdet_name", None)
        if not name:
            return self._termdet
        td = self._termdets.get(name)
        if td is None:
            _, cls = components.select("termdet", name)
            td = self._termdets.setdefault(name, cls())
        return td

    def start(self) -> None:
        """Fire startup hooks of attached pools
        (reference: parsec_context_start:750)."""
        while True:
            with self._lock:
                if not self._pending_start:
                    return
                tp = self._pending_start.pop(0)
            ready = tp.startup()
            if ready:
                scheduling.schedule(self.streams[0], ready)
            tp.ready()
            if self.comm is not None:
                # activations delayed while this pool counted its tasks
                self.comm.retry_delayed()

    def _taskpool_terminated(self, tp: Taskpool) -> None:
        with self._cond:
            self._active_taskpools -= 1
            if self._active_taskpools == 0:
                self._cond.notify_all()

    def test(self) -> bool:
        """Non-blocking completion check (reference: parsec_context_test)."""
        with self._lock:
            return self._active_taskpools == 0

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until all enqueued taskpools complete
        (reference: parsec_context_wait:776).  Past the
        ``runtime_autopsy_s`` soft deadline a one-shot hang autopsy is
        logged so a stuck run is diagnosable from its log."""
        import time as _time
        self.start()
        if self.comm is not None:
            # dynamic pools hold a runtime action until the pool-scoped
            # quiescence round proves every rank drained (see
            # DynamicTaskpool.attach); resolve before waiting on them.
            # timeout=None means wait indefinitely, like the completion
            # wait below — not a default deadline.
            self.comm.resolve_dynamic_holds(timeout)
        start = _time.monotonic()
        autopsy_s = float(params.get("runtime_autopsy_s", 45.0))
        autopsy_at = start + autopsy_s if autopsy_s > 0 else None
        deadline = None if timeout is None else start + timeout
        pred = lambda: self._active_taskpools == 0 or self._errors  # noqa: E731
        while True:
            while True:
                with self._cond:
                    bounds = [t for t in (autopsy_at, deadline)
                              if t is not None]
                    slice_s = max(0.0, min(bounds) - _time.monotonic()) \
                        if bounds else None
                    ok = self._cond.wait_for(pred, timeout=slice_s)
                if ok:
                    break
                now = _time.monotonic()
                if autopsy_at is not None and now >= autopsy_at:
                    from parsec_tpu.utils.output import warning
                    warning("context wait exceeded the %.0fs soft "
                            "deadline — hang autopsy:\n%s", autopsy_s,
                            self.hang_autopsy())
                    autopsy_at = None
                if deadline is not None and now >= deadline:
                    break
            self._raise_first_error()
            if not ok:
                raise TimeoutError("parsec context wait timed out")
            # drain accelerator pipelines: deps are released eagerly on
            # dispatch (devices/xla.py completer), so pool termination
            # means "all work dispatched" — quiescence means "all work
            # done", and late device-side failures surface here
            self.sync_devices(timeout=timeout)
            self._raise_first_error()
            if self.comm is None:
                break
            # distributed: local completion is not global completion —
            # peers may still pull our data (reference: ranks keep
            # progressing comm until termdet quiesces the whole run)
            self.comm.wait_quiescence()
            with self._lock:
                if self._active_taskpools != 0 and not self._errors:
                    # a recovery restart re-armed a pool while the
                    # quiescence round ran (completed-pool grace): the
                    # gang is NOT done — go back to waiting instead of
                    # handing tiles mid-restore to the application
                    continue
                # past global quiescence every completed pool is
                # GLOBALLY done: retire them so a later peer death
                # cannot resurrect them for re-execution
                # (core/recovery.py restarts only locally-complete,
                # not-yet-retired pools)
                for tp in self.taskpools.values():
                    if getattr(tp, "completed", False):
                        tp.retired = True
            break

    def sync_devices(self, timeout: Optional[float] = None) -> None:
        """Quiesce accelerator pipelines (shared by wait() and the job
        service's per-job result path); raises late device failures."""
        for d in self.device_registry.accelerators:
            dsync = getattr(d, "sync", None)
            if dsync is not None:
                dsync(timeout=timeout)

    def _raise_first_error(self) -> None:
        """Surface the first recorded context error.  Structured
        failures (PeerFailedError, TaskRetryExhausted) raise AS
        THEMSELVES when no task is attributable — chaos harnesses and
        serving layers dispatch on the type; everything else keeps the
        pre-existing RuntimeError wrapper."""
        if not self._errors:
            return
        from parsec_tpu.core.errors import (PeerFailedError,
                                            TaskRetryExhausted)
        exc, task = self._errors[0]
        if task is None and isinstance(exc, (PeerFailedError,
                                             TaskRetryExhausted)):
            raise exc
        raise RuntimeError(f"task {task} failed") from exc

    def record_error(self, exc: Exception, task: Task) -> None:
        from parsec_tpu.utils.debug_history import dump_history, paranoid
        if paranoid(1):
            marks = dump_history()
            if marks:
                debug_verbose(1, "debug history (%d marks, newest last):\n%s",
                              len(marks), "\n".join(marks[-64:]))
        # per-pool error isolation (job service): a pool carrying an
        # error_sink keeps its failures to itself — one job's crash must
        # not poison the context for concurrently-running jobs
        from parsec_tpu.core.errors import PeerFailedError
        if isinstance(exc, PeerFailedError):
            # containment fired: capture what just happened before the
            # ring overwrites it (no-op unless the recorder is armed)
            self.telemetry_incident(
                f"PeerFailedError rank={exc.rank} ({exc.detector})")
        tp = getattr(task, "taskpool", None)
        sink = getattr(tp, "error_sink", None) if tp is not None else None
        if sink is not None:
            try:
                sink(exc, task)
                return
            except Exception as sink_exc:   # a broken sink falls back to
                debug_verbose(1, "error_sink failed: %s", sink_exc)
        with self._cond:
            self._errors.append((exc, task))
            self._cond.notify_all()

    def record_pool_error(self, tp, exc: Exception) -> None:
        """Route a pool-scoped failure with no specific task (a dead
        peer, a rendezvous timeout) through the pool's error sink —
        containment for service jobs — falling back to the context-wide
        error list exactly like record_error."""
        self.telemetry_incident(
            f"pool {getattr(tp, 'taskpool_id', '?')} error: "
            f"{type(exc).__name__}")
        sink = getattr(tp, "error_sink", None) if tp is not None else None
        if sink is not None:
            try:
                sink(exc, None)
                return
            except Exception as sink_exc:
                debug_verbose(1, "error_sink failed: %s", sink_exc)
        with self._cond:
            self._errors.append((exc, None))
            self._cond.notify_all()

    def hang_autopsy(self) -> str:
        """One diagnosable snapshot of everything that can wedge a run:
        per-pool termdet counters, comm protocol state (termdet balance,
        parked activations, in-flight rendezvous, per-peer queue depths
        and last-frame ages), and device pipeline depths."""
        lines = ["=== parsec hang autopsy (rank %d) ===" % self.rank]
        with self._lock:
            lines.append(f"active taskpools: {self._active_taskpools}; "
                         f"errors recorded: {len(self._errors)}")
            pools = list(self.taskpools.values())
        for tp in pools:
            if getattr(tp, "completed", False):
                continue
            try:
                peers = sorted(tp.peer_ranks) or "-"
            except RuntimeError:
                # comm threads resize the set lock-free; the autopsy
                # must never raise out of Context.wait
                peers = "~resizing~"
            lines.append(
                f"  pool {tp.taskpool_id} {tp.name!r}: state="
                f"{getattr(tp, 'state', '?')} nb_tasks={tp.nb_tasks} "
                f"pending_actions={tp.nb_pending_actions} "
                f"cancelled={tp.cancelled} "
                f"peer_ranks={peers}")
        done = sum(es.nb_tasks_done for es in self.streams)
        lines.append(f"workers: {len(self.streams)} streams, "
                     f"{done} tasks done")
        for d in self.device_registry.accelerators:
            pend = len(getattr(d, "_pending", ()) or ())
            infl = len(getattr(d, "_inflight", ()) or ())
            held = len(getattr(d, "_held", ()) or ())
            lines.append(f"  device {d.name}: pending={pend} "
                         f"inflight={infl} held={held}")
        if self.comm is not None:
            dbg = getattr(self.comm, "debug_state", None)
            if dbg is not None:
                try:
                    lines.append("comm: " + repr(dbg()))
                except Exception as exc:   # the autopsy must never raise
                    lines.append(f"comm: <debug_state failed: {exc}>")
        # control-plane tail: the last ~N protocol events per rank,
        # clock-aligned — a wedged negotiation (a mode vote that never
        # got its quorum, a need round nobody answered) is visible in
        # the autopsy text itself, no bundle pull needed
        tail_n = int(params.get("journal_autopsy_tail", 20))
        if tail_n > 0 and getattr(self, "journal", None) is not None:
            try:
                from parsec_tpu.prof.journal import (cluster_journals,
                                                     format_event,
                                                     merge_journals)
                per_rank = cluster_journals(self, timeout=2.0)
                for r in sorted(per_rank):
                    snap = per_rank[r]
                    snap["events"] = snap.get("events", [])[-tail_n:]
                merged = merge_journals(per_rank)
                if merged:
                    t0 = merged[0]["t"]
                    lines.append("control-plane journal tail "
                                 f"(last {tail_n}/rank, clock-aligned):")
                    lines.extend("  " + format_event(ev, t0)
                                 for ev in merged)
            except Exception as exc:   # the autopsy must never raise
                lines.append(f"journal tail: <failed: {exc}>")
        # armed flight recorder: the last-N-seconds ring is worth more
        # than this snapshot — dump it and point the reader at the
        # bundle (merge with tools/trace2chrome.py --merge)
        bundle = self.telemetry_incident("hang-autopsy")
        if bundle is not None:
            lines.append(f"flight recorder incident bundle: {bundle} "
                         "(open: python tools/trace2chrome.py --merge "
                         f"{bundle}/rank*.ptt)")
        return "\n".join(lines)

    # -- remote deps (filled in by the comm layer) ------------------------
    def remote_dep_activate(self, es, task, flow, dep, succ_tc, succ_locals,
                            copy) -> None:
        if self.comm is None:
            from parsec_tpu.utils.output import show_help
            raise RuntimeError(
                f"{task}: successor {succ_tc.name}{succ_locals} lives on "
                f"rank {succ_tc.rank_of(succ_locals)}.\n"
                + show_help("no-comm-engine", warn=False))
        self.comm.remote_dep_activate(es, task, flow, dep, succ_tc,
                                      succ_locals, copy)

    # -- shutdown ----------------------------------------------------------
    def fini(self) -> None:
        """Stop workers (reference: parsec_fini)."""
        with self._cond:
            self.finished = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        self.device_registry.fini()
        stats = self.scheduler.display_stats(None)
        if stats:
            inform("scheduler stats: %s", stats)
        for mod in getattr(self, "_pins_modules", []):
            disp = getattr(mod, "display", None)
            if disp is not None:
                inform("pins %s: %s", type(mod).__name__, disp())
            unins = getattr(mod, "uninstall", None)
            if unins is not None:   # reference: pins_fini unregisters
                unins(self)
        if self.metrics is not None:
            self.metrics.uninstall(self)
        if self._flightrec is not None:
            self._flightrec.uninstall(self)
        jdir = str(params.get("journal_dir", "") or "").strip()
        jr = getattr(self, "journal", None)
        if jdir and jr is not None and jr.enabled:
            # per-rank black-box bundle for tools/journal_audit.py
            # (chaos --audit-journal arms this per case).  A DISABLED
            # journal dumps nothing at all — a header-only file would
            # let an audit pass vacuously over zero events
            try:
                jr.dump(jdir)
            except OSError as exc:
                debug_verbose(1, "journal dump failed: %s", exc)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.fini()
        return False
