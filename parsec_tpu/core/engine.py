"""Dependency-resolution engine: the release-deps / activate-successors path.

Rebuild of the reference's generic dep engine (reference: parsec.c:1694-1894
``parsec_release_local_OUT_dependencies`` / ``parsec_release_dep_fct`` and
the hashed dependency tracking of parsec_hash_find_deps): when a task
completes, its output deps are evaluated; each local successor's
dep-countdown record accumulates arrivals (with the produced data copies
attached) and the successor instantiates exactly when the count reaches its
expected number of task-fed inputs.  Remote successors are handed to the
comm layer (remote-dep activation).

All countdown mutations ride the deps-table bucket locks, mirroring the
reference's atomic update_deps_with_counter (parsec_internal.h:355-366).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from parsec_tpu.containers.hash_table import REMOVE
from parsec_tpu.data.data import (ACCESS_READ, ACCESS_WRITE, Coherency, Data,
                                  DataCopy, FLAG_COW, FLAG_SCRATCH)
from parsec_tpu.data.reshape import as_dtt, convert, needs_reshape
from parsec_tpu.core.task import (Dep, Flow, FromDesc, FromTask, New, Null,
                                  Task, TaskClass, ToDesc, ToTask)
from parsec_tpu.utils.debug_history import paranoid
from parsec_tpu.utils.mempool import MemoryPool
from parsec_tpu.utils.output import warning

import numpy as np


class PendingRecord:
    """Dep-countdown record for a not-yet-ready task
    (reference: parsec_dependency_t in hash mode)."""

    __slots__ = ("expected", "arrivals", "inputs", "sources", "locals")

    def __init__(self, expected: int, locals_: Dict[str, int]):
        self.expected = expected
        self.arrivals = 0
        self.inputs: Dict[str, Optional[DataCopy]] = {}
        self.sources: Dict[str, Tuple[TaskClass, Tuple]] = {}
        self.locals = locals_


def _rec_reset(rec: PendingRecord) -> None:
    # drop references only: the Task constructed at readiness ALIASES
    # rec.locals and copied the inputs/sources entries — clearing these
    # slots must not clear the dicts themselves
    rec.expected = 0
    rec.arrivals = 0
    rec.inputs = {}
    rec.sources = {}
    rec.locals = None


#: hot-path record pool (reference: the task/dep mempools of
#: parsec/mempool.c — one countdown record is allocated per not-yet-ready
#: task instance and freed the moment the task becomes ready)
_rec_pool = MemoryPool(factory=lambda: PendingRecord(0, None),
                       reset=_rec_reset)


def deliver_dep(taskpool, succ_tc: TaskClass, succ_locals: Dict[str, int],
                flow_name: str, copy: Optional[DataCopy],
                source: Optional[Tuple[TaskClass, Tuple]]) -> Optional[Task]:
    """Record one dependency arrival at a local successor; return the
    instantiated Task exactly when it becomes ready."""
    # dep expressions may address peers by their FREE parameters only;
    # derived single-value params (JDF derived-local idiom) are filled
    # here so the instantiated task carries the full local set
    succ_locals = succ_tc.complete_locals(succ_locals)
    key = succ_tc.make_key(succ_locals)

    nd = taskpool._native_deps
    if nd is not None:
        # native dep-countdown (parsec_tpu/native/schedext.c DepTable,
        # gated by sched_native): the counter decrement, the input/
        # source recording, and the ready-transition test ride one C
        # crossing per arrival (two on the first, which installs the
        # record).  The GIL is the bucket lock; create() keeps an
        # existing record, so two workers racing the first arrivals
        # cannot wipe each other's count.
        res = nd.arrive(key, flow_name, copy, source)
        if res is False:
            nd.create(key, succ_tc.nb_task_inputs(succ_locals),
                      dict(succ_locals))
            res = nd.arrive(key, flow_name, copy, source)
        if res is None:
            return None
        locals_, inputs, sources = res
        # C task construction when the vtable exists: the record's
        # locals dict is exclusively owned (created at nd.create,
        # dropped with the record), so the constructor may alias it
        vt = succ_tc.native_vt()
        task = vt.build_one(locals_) if vt is not None \
            else Task(succ_tc, taskpool, locals_)
        if taskpool.dynamic:
            # see the non-native branch below for the ordering contract
            # (dynamic pools are statically OFF the C chain, so this
            # per-task move only runs where correctness needs it)
            taskpool.termdet.taskpool_addto_nb_tasks(  # lint: ignore[PCL-HOT]
                taskpool, 1)
        if inputs is not None:
            task.data.update(inputs)
            task.pinned_flows.update(k for k, v in inputs.items()
                                     if v is not None)
        if sources is not None:
            task.input_sources.update(sources)
        return task

    def fn(rec):
        if rec is None:
            rec = _rec_pool.alloc()
            rec.expected = succ_tc.nb_task_inputs(succ_locals)
            rec.locals = dict(succ_locals)
        rec.arrivals += 1
        if paranoid(2) and rec.arrivals > rec.expected:
            raise AssertionError(
                f"{succ_tc.name}{succ_locals}: {rec.arrivals} arrivals "
                f"exceed the expected {rec.expected} task-fed inputs")
        if copy is not None and rec.inputs.get(flow_name) is not None:
            # JDF forbids data gathers: a data flow has exactly one source
            raise RuntimeError(
                f"{succ_tc.name}{succ_locals}: data flow {flow_name!r} "
                "received two copies — range deps may only gather CTL")
        rec.inputs[flow_name] = copy
        if source is not None:
            rec.sources[flow_name] = source
        if rec.arrivals >= rec.expected:
            return REMOVE, rec
        return rec, None

    rec = taskpool.deps_table.mutate(key, fn)
    if rec is None:
        return None
    task = Task(succ_tc, taskpool, rec.locals)
    if taskpool.dynamic:
        # dynamically-discovered pools count tasks as they materialize
        # (reference: dynamic termdet, ptgpp --dynamic-termdet); the +1
        # precedes the producer's -1 in complete_execution, so the count
        # cannot transiently hit zero mid-discovery — and dynamic pools
        # never ride the C chain, so the locked move is correctness-only
        taskpool.termdet.taskpool_addto_nb_tasks(  # lint: ignore[PCL-HOT]
            taskpool, 1)
    task.data.update(rec.inputs)
    task.pinned_flows.update(k for k, v in rec.inputs.items()
                             if v is not None)
    task.input_sources.update(rec.sources)
    _rec_pool.release(rec)
    return task


def prepare_input(es, task: Task) -> None:
    """Bind every input flow to a concrete data copy
    (reference: generated data_lookup, jdf2c.c:43).

    Task-fed flows were bound at delivery time; collection reads resolve
    through the coherency protocol; NEW flows allocate from the arena.
    """
    tp = task.taskpool
    tc = task.task_class
    data = task.data
    # flows with no input deps can only bind None (class-partitioned
    # once, core/task.py); the resolution loop walks the rest
    for name in tc._noin_flow_names:
        if name not in data:
            data[name] = None
    for flow in tc._in_flows:
        if flow.name in task.data:
            continue
        dep = flow.active_input(task.locals)
        if dep is None or isinstance(dep.end, Null):
            task.data[flow.name] = None
            continue
        end = dep.end
        if isinstance(end, FromDesc):
            ref = end.ref_fn(task.locals)
            datum = ref.resolve()
            copy = datum.copy_on(0)
            if copy is None:
                raise RuntimeError(f"{task}: no host copy for {ref}")
            # Bind only; coherency (and any pull) is resolved at the
            # execution site — stage_in_host for CPU incarnations, the
            # device module's stage-in for accelerator ones — so a tile
            # resident on the device that will run the task moves zero
            # bytes (reference: the data_lookup / stage_in split).
            dtt = as_dtt(dep.dtt)
            if dtt is not None and needs_reshape(copy, dtt):
                # converting read from the collection (reference:
                # parsec_get_copy_reshape_from_desc)
                copy = tp.reshape.get_copy(copy, dtt)
            task.data[flow.name] = copy
        elif isinstance(end, New):
            arena = tp.arenas.get(end.arena_name)
            if arena is None:
                raise RuntimeError(
                    f"{task}: flow {flow.name} needs arena "
                    f"{end.arena_name!r} but the taskpool has none")
            copy = arena.get_copy()
            # the buffer is np.empty scratch: nothing may read it before
            # the first write, so a device incarnation can materialize it
            # directly in device memory (see XlaDevice._stage_in)
            copy.flags |= FLAG_SCRATCH
            task.data[flow.name] = copy
        elif isinstance(end, FromTask):
            if dep.multiplicity(task.locals) == 0:
                # empty JDF range at a boundary instance: no edge, no data
                task.data[flow.name] = None
                continue
            raise RuntimeError(
                f"{task}: task-fed flow {flow.name} reached prepare_input "
                f"unbound — activation protocol error")
        else:
            task.data[flow.name] = None


def stage_in_host(task: Task) -> None:
    """Make every bound data flow valid on the host before a CPU body runs
    (the host-side analog of the device module's stage-in; reference:
    generated data_lookup resolving CPU-side copies).  Pulls from a
    newer device-resident copy when one exists and rebinds the flow to
    the host copy so in-place numpy mutation works.

    A bound copy that is no longer attached to its datum is a
    version-pinned snapshot: a same-wavefront ``-> DATA`` writeback
    superseded it (see _writeback), and the consumer must read the
    snapshot — not the datum's newer copy (reference: repo-pinned
    versioned copies, datarepo.h:50-58)."""
    for flow in task.task_class.flows:
        copy = task.data.get(flow.name)
        if copy is None or copy.data is None:
            continue
        p = copy.payload
        if getattr(p, "parsec_deferred", False):
            # a chain-held device task's output reached a CPU body:
            # dispatch the held chain now (devices/xla.py Deferred)
            copy.payload = p.force()
        datum = copy.data
        with datum._lock:
            if copy.flags & FLAG_COW:
                # materialize the private buffer before the body writes
                copy.payload = np.asarray(copy.payload).copy()
                copy.flags &= ~FLAG_COW
            if copy.is_pinned_snapshot(flow.name in task.pinned_flows):
                # read the bound payload, never the datum's newer copy
                if not isinstance(copy.payload, np.ndarray):
                    copy.payload = np.asarray(copy.payload)
                if flow.access & ACCESS_WRITE:
                    # the snapshot payload may alias storage other pinned
                    # readers hold (e.g. the old backing view): a writing
                    # body must get a private buffer
                    copy.payload = copy.payload.copy()
                continue
            host = datum.copy_on(0)
            if host is None:
                host = datum.create_copy(0)
            src = datum.transfer_ownership(0, flow.access)
            if src is not None:
                sp_ = src.payload
                if getattr(sp_, "parsec_deferred", False):
                    src.payload = sp_.force()
                arr = np.asarray(src.payload)
                if host.payload is None or \
                        not isinstance(host.payload, np.ndarray) or \
                        not host.payload.flags.writeable:
                    host.payload = arr.copy()
                else:
                    np.copyto(host.payload, arr)
                host.version = src.version
            elif host.payload is None and copy.payload is not None \
                    and copy is not host:
                host.payload = np.asarray(copy.payload).copy()
                host.version = copy.version
        task.data[flow.name] = host


def _writeback(task: Task, flow: Flow, copy: DataCopy, ref,
               dtt=None) -> None:
    """Return a produced copy to its collection datum (``-> A(m, n)``).

    A copy that already belongs to the datum needs NO data movement — in
    particular a device-resident copy simply stays the authoritative
    version (the reference keeps GPU copies resident until eviction or
    flush, not eagerly D2H on every output dep); host readers pull it
    lazily via Data.pull_to_host.  Only a copy of a *different* datum
    (arena temporaries, COW duplicates) is physically written back.

    The write-back NEVER mutates the existing host copy's storage: a
    same-wavefront reader bound to that copy would observe the new value
    mid-read (the stencil Gauss–Seidel contamination).  Instead the old
    host copy is detached — surviving, version-pinned, for any consumer
    already holding it — and a fresh copy with a private payload becomes
    the datum's new authoritative version (reference: versioned
    data-copies + repo refcount protocol, datarepo.h:50-58).
    """
    datum = ref.resolve()
    home_dtype = getattr(datum.collection, "dtype", None)
    if copy.data is datum and datum.copy_on(copy.device) is copy \
            and (dtt is None or not needs_reshape(copy, dtt)) \
            and (dtt is None or dtt.inverse is None) \
            and (home_dtype is None or
                 getattr(copy.payload, "dtype", home_dtype) == home_dtype):
        # attached and already in home type: in place (host) or
        # device-resident (lazy pull-home).  A DETACHED copy of the same
        # datum is a superseded snapshot a WRITE body mutated privately —
        # its value must still land below or the update is silently lost;
        # an edge-layout (dtt) copy — or a body that rebound the attached
        # copy to the EDGE dtype (dtype-only OUT dtt) — must be converted
        # home below or the collection silently keeps the stale value.
        return
    if dtt is not None:
        # reshape-on-writeback: undo the edge's layout transform
        # (reference: the reverse reshape of parsec_reshape.c remote/
        # local writeback paths)
        arr = np.asarray(convert(copy.payload, dtt, inverse=True)).copy()
    else:
        arr = np.asarray(copy.payload).copy()
    # ToDesc writeback is statically OFF the C chain (OBAIL): this lock
    # guards the descriptor's copy table on the Python-only path
    with datum._lock:   # lint: ignore[PCL-HOT]
        old = datum.copy_on(0)
        # the collection's dtype is authoritative at home; the old host
        # copy's dtype is only a fallback — the body may have rebound
        # that copy to the EDGE dtype already (dtype-only OUT dtt)
        want = home_dtype if home_dtype is not None else \
            (getattr(old.payload, "dtype", None) if old is not None
             else None)
        if want is not None and arr.dtype != want:
            # the collection's dtype is authoritative at home (bf16
            # compute edges land back in the f32 collection)
            arr = arr.astype(want)
        check_versions = paranoid(2)   # sample ONCE: the tier may move
        old_v = datum.newest_version() if check_versions else 0
        datum.detach_copy(0)   # readers keep their pinned snapshot
        for c in datum.copies().values():
            c.coherency = Coherency.INVALID
        host = DataCopy(datum, 0, payload=arr,
                        coherency=Coherency.EXCLUSIVE)
        datum.attach_copy(host)
        datum._version_clock += 1
        host.version = datum._version_clock
        if check_versions and host.version <= old_v:
            raise AssertionError(
                f"writeback of {datum} did not advance the version clock "
                f"({old_v} -> {host.version})")
    # the user-visible backing array re-links at quiescence, when no
    # pinned reader of the old view can still be in flight
    if datum.collection is not None:
        task.taskpool.dirty_data.add(datum)


def release_deps(es, task: Task) -> List[Task]:
    """Evaluate output deps of a completed task, deliver to successors,
    manage repo lifetime; return newly-ready local tasks
    (reference: generated release_deps + iterate_successors,
    jdf2c.c:7175,7631 -> parsec.c:1783)."""
    tp = task.taskpool
    tc = task.task_class
    myrank = tp.context.rank if tp.context else 0
    grapher = tp.context.grapher if tp.context else None
    ready: List[Task] = []
    consumers = 0
    entry = None
    #: arena-backed copies whose only consumers are remote: nothing local
    #: creates a repo entry for them, so they are returned to the freelist
    #: once flush_activations has serialized the payload (ADVICE r1: the
    #: QR NEW-temporary leak on distributed runs)
    remote_only_arena: List[DataCopy] = []

    #: minimal-replay restart gate (core/recovery.py): local deliveries
    #: to consumers outside the replay plan are redundant re-sends of
    #: already-materialized work — skipping them HERE (not in
    #: deliver_dep) also keeps them out of the repo usage count, so the
    #: producer's entry still retires.  Remote activations always fire;
    #: the receiving rank's own filter decides there.
    replay_filter = tp._replay_filter

    # only flows with output deps can deliver anything (class-level
    # partition, core/task.py): a CTL-only or sink flow skips the whole
    # delivery bookkeeping below
    for flow in tc._out_flows:
        copy = task.data.get(flow.name)
        # gather this flow's local deliveries first: a copy fanning out to
        # several consumers must hand any WRITE-consumer a copy-on-write
        # duplicate, or its in-place update races the other readers
        # (reference: data-copy duplication for RW flows on shared copies)
        local_deliveries: List[Tuple] = []
        remote_count = 0
        for dep in flow.active_outputs(task.locals):
            end = dep.end
            if isinstance(end, ToDesc):
                if copy is not None:
                    _writeback(task, flow, copy, end.ref_fn(task.locals),
                               dtt=as_dtt(dep.dtt))
            elif isinstance(end, ToTask):
                succ_tc = tp.task_classes[end.task_class]
                for succ_locals in end.instances(task.locals):
                    # dep expressions address peers by free params; fill
                    # derived ones NOW — rank_of/make_key below may need
                    # them (e.g. an affinity over a derived local)
                    succ_locals = succ_tc.complete_locals(succ_locals)
                    if grapher is not None:
                        grapher.edge(task, succ_tc.make_key(succ_locals),
                                     flow.name)
                    if succ_tc.rank_of(succ_locals) != myrank:
                        tp.context.remote_dep_activate(
                            es, task, flow, dep, succ_tc, succ_locals, copy)
                        remote_count += 1
                        continue
                    if replay_filter is not None and \
                            succ_tc.make_key(succ_locals) \
                            not in replay_filter:
                        continue   # consumer not re-enumerated (minimal)
                    local_deliveries.append(
                        (succ_tc, succ_locals, end.flow, dep))
            # Null outputs: data is discarded (arena copies will be
            # released by the repo retirement below, or were views)
        total = len(local_deliveries) + remote_count
        if copy is None and total > 0 and flow.access != 0:
            # a data (non-CTL) flow handing None downstream: legal — the
            # successor's input binds NULL — but almost always a graph
            # bug, so flag it like the reference does (ptgpp
            # forward_{READ,RW}_NULL golden behavior)
            warning("A NULL is forwarded from %s flow %s to %d "
                    "successor(s)", task, flow.name, total)
        if remote_count and not local_deliveries and copy is not None \
                and copy.arena is not None:
            remote_only_arena.append(copy)
        ici = tp.context.ici if tp.context is not None else None
        if copy is not None and ici is not None and local_deliveries \
                and (len(local_deliveries) > 1
                     or ici.device_resident(copy)):
            # Fan-out onto DISTINCT consumer devices: one collective
            # replication; a single distinct target (one consumer, or
            # several sharing a device): one proactive d2d put that
            # overlaps with scheduling (reference: dataflow bcast trees
            # remote_dep.c:334-357 and the CE put; SURVEY §5.8 ICI
            # lowering).  Host-resident single-consumer edges — the
            # dominant same-device case — skip the affinity resolution
            # entirely; multi-consumer fan-outs qualify even from host
            # (one replication beats N separate stage-ins).
            uniq = set(ici.consumer_spaces(
                tp, [d[:3] for d in local_deliveries]))
            uniq.discard(copy.device)
            if len(uniq) > 1:
                ici.prebroadcast(copy, sorted(uniq))
            elif len(uniq) == 1:
                # single-consumer edge: defer so the whole DAG wavefront
                # (stencil halos, ring neighbor hops, panel sends) rides
                # ONE batched CollectivePermute instead of N puts
                # (SURVEY §5.8); host-resident copies fall through to
                # lazy stage-in as before
                sp = uniq.pop()
                if not ici.defer_place(copy, sp):
                    ici.preplace(copy, sp)
        for succ_tc, succ_locals, dflow, odep in local_deliveries:
            dcopy = copy
            if copy is not None:
                # edge datatype: the consumer's IN dtt wins, else the
                # producer's OUT dtt (reference: receiver-side datatype
                # lookup, remote_dep_get_datatypes)
                edge_dtt = _edge_dtt(succ_tc, dflow, succ_locals) \
                    or as_dtt(odep.dtt)
                if edge_dtt is not None and needs_reshape(copy, edge_dtt):
                    dcopy = tp.reshape.get_copy(copy, edge_dtt)
            if dcopy is not None and total > 1 and \
                    succ_tc.flow(dflow).access & ACCESS_WRITE:
                dcopy = _cow_copy(dcopy)
            if entry is None and copy is not None:
                entry = tc.repo.lookup_entry_and_create(task.key)
            if copy is not None:
                if entry.copies[flow.flow_index] is not copy \
                        and copy.arena is not None:
                    # entry hold on the arena buffer: a NEW-flow copy
                    # chained through several tasks lives in every
                    # producer's entry, and only the LAST retirement may
                    # return it to the freelist (reference: refcounted
                    # repo copies, datarepo.h:50-58)
                    copy.arena.retain_copy(copy)
                entry.copies[flow.flow_index] = copy
                consumers += 1
            src = (tc, task.key) if copy is not None else None
            es.pins("deliver_dep", (task, succ_tc, succ_locals, dflow))
            t = deliver_dep(tp, succ_tc, succ_locals, dflow, dcopy, src)
            if t is not None:
                ready.append(t)

    if entry is not None:
        entry.on_retire = _make_retire(task)
        tc.repo.entry_addto_usage_limit(task.key, consumers)

    # dynamically-discovered pools (DTD) resolve successors from their
    # runtime dep graph rather than from flow expressions
    dynamic = getattr(tp, "dynamic_release", None)
    if dynamic is not None:
        ready.extend(dynamic(es, task))

    # ship buffered remote activations as one message per flow down the
    # bcast tree (reference: parsec_remote_dep_activate after
    # iterate_successors filled the rank bitmask)
    if tp.context is not None and tp.context.comm is not None:
        tp.context.comm.flush_activations(es, task)
        # flush serialized every outgoing payload synchronously: arena
        # temporaries with no local consumer can go home now — unless an
        # earlier producer's repo entry still holds the chained buffer
        for copy in remote_only_arena:
            if copy.data is not None:
                copy.data.detach_copy(copy.device)
            copy.arena.release_unheld(copy)
    return ready


def _edge_dtt(succ_tc: TaskClass, dflow: str, succ_locals: Dict[str, int]):
    """The consumer-side dtt of a task-fed edge, if any."""
    flow = succ_tc.flow(dflow)
    if flow is None:
        return None
    dep = flow.active_input(succ_locals)
    return as_dtt(dep.dtt) if dep is not None else None


def _cow_copy(copy: DataCopy) -> DataCopy:
    """A lazily-duplicating alias of ``copy``: shares the payload now, but
    carries FLAG_COW so the execution site (stage_in_host, or the device
    stage-in) materializes a private buffer before any write or donation."""
    datum = Data(nb_elts=copy.data.nb_elts if copy.data is not None else 0)
    # registered at host index regardless of where the shared payload
    # lives: both stage_in_host and the device stage-in then see it as
    # "the newest copy" and materialize a private buffer from it
    c = DataCopy(datum, 0, payload=copy.payload,
                 coherency=Coherency.EXCLUSIVE, version=1)
    c.flags = FLAG_COW
    datum.attach_copy(c)
    return c


def _make_retire(task: Task):
    def retire(entry):
        for copy in entry.copies:
            if copy is not None and copy.arena is not None:
                copy.arena.drop_copy(copy)
    return retire


def consume_inputs(task: Task) -> None:
    """Release our holds on predecessor repo entries
    (reference: data_repo_entry_used_once calls in generated release_deps)."""
    for flow_name, (ptc, pkey) in task.input_sources.items():
        ptc.repo.entry_used_once(pkey)
