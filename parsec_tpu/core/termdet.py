"""Termination detection modules.

Rebuild of the reference's termdet MCA framework
(reference: parsec/mca/termdet/termdet.h state machine
NOT_MONITORED -> NOT_READY -> BUSY -> IDLE -> TERMINATED, and the rule that
a taskpool's nb_tasks / nb_pending_actions may only move through the
module, parsec_internal.h:123-143).

``LocalTermdet`` is the default single-process module (reference:
termdet/local): termination fires when both counters reach zero after the
taskpool was made ready.  The distributed four-counter module lives in
parsec_tpu/comm once the comm engine exists; it plugs into the same
interface.
"""

from __future__ import annotations

import threading
from enum import IntEnum
from typing import Callable, Optional

from parsec_tpu.utils.mca import components


class TermdetState(IntEnum):
    NOT_MONITORED = 0
    NOT_READY = 1      # counters may move, termination cannot fire yet
    BUSY = 2
    IDLE = 3
    TERMINATED = 4


class Termdet:
    """Module interface (reference: parsec_termdet_module_t)."""

    name = "base"

    def monitor(self, taskpool, on_termination: Callable[[], None]) -> None:
        raise NotImplementedError

    def unmonitor(self, taskpool) -> None:
        pass

    def taskpool_ready(self, taskpool) -> None:
        raise NotImplementedError

    def taskpool_addto_nb_tasks(self, taskpool, delta: int,
                                epoch: Optional[int] = None) -> int:
        """``epoch`` carries a BATCHED delta's recovery generation
        (core/scheduling's per-worker accumulators): the delta applies
        only while ``taskpool.run_epoch`` still matches — a flush
        racing a recovery restart drops its torn-generation counts
        under the module lock instead of corrupting the re-counted
        pool (the rewind/generation-fence contract)."""
        raise NotImplementedError

    def taskpool_addto_runtime_actions(self, taskpool, delta: int) -> int:
        raise NotImplementedError

    def taskpool_force_quiesce(self, taskpool) -> None:
        """Cancellation support (job service): zero the counters and fire
        termination immediately, regardless of undelivered tasks.  After
        this, late decrements from in-flight tasks of the (cancelled)
        pool must clamp at zero instead of going negative."""
        raise NotImplementedError(
            f"termdet {self.name!r} does not support cancellation")

    def taskpool_reset(self, taskpool, force_terminated: bool = False):
        """Recovery support (core/recovery.py): zero the counters and
        rewind the state machine to NOT_READY WITHOUT firing
        termination, so the pool can be re-enumerated and re-run after
        a peer death.  Returns the PRE-reset TermdetState, or None when
        the rewind was refused.  A TERMINATED pool is refused by
        default; ``force_terminated`` rewinds it anyway — the recovery
        plane needs that for pools that completed LOCALLY while the
        gang still needs their re-executed partition (local completion
        is not global completion), and uses the returned TERMINATED to
        re-take the context's active count.  Stale decrements from
        pre-recovery tasks are fenced by the pool's run_epoch, not by
        the termdet."""
        raise NotImplementedError(
            f"termdet {self.name!r} does not support recovery reset")

    # message-counting hooks for distributed modules (no-ops locally;
    # reference: termdet.h:171-243)
    def outgoing_message_start(self, taskpool, dst: int) -> None:
        pass

    def incoming_message_end(self, taskpool, src: int) -> None:
        pass


class LocalTermdet(Termdet):
    """Counter-based local termination (reference: termdet/local module)."""

    name = "local"

    def __init__(self):
        self._lock = threading.Lock()
        self._state: dict = {}

    def monitor(self, taskpool, on_termination: Callable[[], None]) -> None:
        with self._lock:
            self._state[id(taskpool)] = {
                "state": TermdetState.NOT_READY,
                "cb": on_termination,
            }

    def unmonitor(self, taskpool) -> None:
        with self._lock:
            self._state.pop(id(taskpool), None)

    def _check(self, taskpool, st) -> bool:
        return (st["state"] == TermdetState.BUSY
                and taskpool.nb_tasks == 0
                and taskpool.nb_pending_actions == 0)

    def taskpool_ready(self, taskpool) -> None:
        fire = False
        with self._lock:
            st = self._state[id(taskpool)]
            if st["state"] == TermdetState.NOT_READY:
                st["state"] = TermdetState.BUSY
                fire = self._check(taskpool, st)
                if fire:
                    st["state"] = TermdetState.TERMINATED
        if fire:
            st["cb"]()

    def _addto(self, taskpool, field: str, delta: int,
               epoch: Optional[int] = None) -> int:
        fire = False
        with self._lock:
            st = self._state.get(id(taskpool))
            if epoch is not None and \
                    epoch != getattr(taskpool, "run_epoch", 0):
                # torn-generation batch flush: the pool restarted after
                # these decrements accumulated; the restart re-counted
                # nb_tasks from scratch, so the stale delta must drop.
                # Checked under the lock: taskpool_reset serializes on
                # it, so a matching epoch here cannot be zeroed away
                # between this check and the apply below
                return getattr(taskpool, field)
            setattr(taskpool, field, getattr(taskpool, field) + delta)
            val = getattr(taskpool, field)
            if val < 0:
                if getattr(taskpool, "cancelled", False):
                    # force_quiesce already zeroed the counters; late
                    # decrements from tasks that were in flight at
                    # cancellation clamp instead of going negative
                    setattr(taskpool, field, 0)
                    val = 0
                else:
                    raise RuntimeError(
                        f"{field} of {taskpool} went negative ({val})")
            if st is not None and self._check(taskpool, st):
                st["state"] = TermdetState.TERMINATED
                fire = True
        if fire:
            st["cb"]()
        return val

    def taskpool_addto_nb_tasks(self, taskpool, delta: int,
                                epoch: Optional[int] = None) -> int:
        return self._addto(taskpool, "nb_tasks", delta, epoch)

    def taskpool_addto_runtime_actions(self, taskpool, delta: int) -> int:
        return self._addto(taskpool, "nb_pending_actions", delta)

    def taskpool_force_quiesce(self, taskpool) -> None:
        """Zero the counters and fire termination now (cancellation; see
        Taskpool.cancel).  Safe against concurrent normal termination:
        the state machine fires the callback exactly once."""
        fire = False
        with self._lock:
            st = self._state.get(id(taskpool))
            taskpool.nb_tasks = 0
            taskpool.nb_pending_actions = 0
            if st is not None and st["state"] in (TermdetState.NOT_READY,
                                                  TermdetState.BUSY):
                st["state"] = TermdetState.TERMINATED
                fire = True
        if fire:
            st["cb"]()

    def taskpool_reset(self, taskpool, force_terminated: bool = False):
        """Zero the counters and rewind to NOT_READY without firing
        (recovery re-execution; see Termdet.taskpool_reset).  Returns
        the pre-reset state, or None when refused: a TERMINATED pool is
        only rewound under ``force_terminated`` (the caller then owns
        re-arming the completion bookkeeping its termination already
        released)."""
        with self._lock:
            st = self._state.get(id(taskpool))
            if st is None:
                return None
            prev = st["state"]
            if prev == TermdetState.TERMINATED and not force_terminated:
                return None
            taskpool.nb_tasks = 0
            taskpool.nb_pending_actions = 0
            st["state"] = TermdetState.NOT_READY
            return prev


class UserTriggerTermdet(LocalTermdet):
    """Termination declared by an explicit user call, propagated to every
    rank over its own message tag (reference:
    mca/termdet/termdet_user_trigger_module.c) — for irregular apps whose
    task count is unknowable up front (the haar-tree/project_dyn pattern:
    tasks keep discovering tasks until the algorithm decides it is done).

    Counters are still tracked (and guarded against going negative), but
    ZERO COUNTERS NEVER FIRE termination — only ``trigger`` does.  On the
    triggering rank the call broadcasts to all peers; each rank fires its
    local pool.
    """

    name = "user_trigger"

    def _check(self, taskpool, st) -> bool:
        return False    # only trigger() terminates

    def trigger(self, taskpool, propagate: bool = True) -> None:
        """Declare the taskpool terminated (reference:
        parsec_termdet_user_trigger... the root's write of the
        termination word)."""
        ctx = taskpool.context
        if propagate and ctx is not None and ctx.comm is not None:
            ctx.comm.send_user_trigger(taskpool.taskpool_id)
        fire = False
        with self._lock:
            st = self._state.get(id(taskpool))
            if st is not None and st["state"] != TermdetState.TERMINATED:
                st["state"] = TermdetState.TERMINATED
                fire = True
        if fire:
            st["cb"]()


components.add("termdet", "local", LocalTermdet, priority=50)
components.add("termdet", "user_trigger", UserTriggerTermdet, priority=10)
