"""Structured runtime failures (the robustness substrate's vocabulary).

The reference aborts the job on any comm failure (PaRSEC has no fault
tolerance in-tree); a resident serving runtime instead needs failures
that NAME what broke so containment can route them: a dead peer fails
the jobs whose taskpools touch that rank, an exhausted retry fails one
task's pool, and everything else keeps running.  These classes subclass
the exceptions the pre-existing paths raised (ConnectionError /
RuntimeError), so every ``except`` written against the old vocabulary
still catches them.
"""

from __future__ import annotations

from typing import Optional


class PeerFailedError(ConnectionError):
    """A peer rank died mid-run (hard socket close, protocol corruption,
    or heartbeat silence past ``comm_peer_timeout_s``).  ``rank`` is the
    dead peer; ``detector`` says which path declared it (``"close"``,
    ``"corrupt"``, ``"heartbeat"``, ``"connect"``, ``"rendezvous"``)."""

    def __init__(self, rank: int, msg: str, detector: str = "close"):
        super().__init__(msg)
        self.rank = rank
        self.detector = detector


class TaskRetryExhausted(RuntimeError):
    """A transiently-failing task was retried ``attempts`` times
    (``task_retry_max``) and still failed; ``__cause__`` carries the
    last body error."""

    def __init__(self, msg: str, attempts: int = 0,
                 last: Optional[BaseException] = None):
        super().__init__(msg)
        self.attempts = attempts
        if last is not None:
            self.__cause__ = last


class CheckpointDegradedError(RuntimeError):
    """A checkpoint/restore was attempted while a peer rank is dead and
    NOT routed-around by recovery: the collective barrier delimiting
    the snapshot would wedge until its timeout, so the operation fails
    fast instead.  ``ranks`` names the dead peers."""

    def __init__(self, msg: str, ranks=()):
        super().__init__(msg)
        self.ranks = sorted(ranks)


class FaultInjected(RuntimeError):
    """A fault-plan ``fail_task`` directive fired (utils/faultinject.py).
    Deliberately transient-shaped: the retry machinery treats it like
    any other body error."""
