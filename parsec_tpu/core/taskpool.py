"""Taskpools: DAG containers with lifecycle and termination detection.

Rebuild of the reference's taskpool object
(reference: parsec/parsec_internal.h:119-161 ``parsec_taskpool_t``,
scheduling.c:678-727 add_taskpool, compound.c): a taskpool owns task
classes, global symbols, arenas, and the two termination counters
(``nb_tasks`` = known-but-unexecuted tasks, ``nb_pending_actions`` =
runtime activities incl. the pool's own startup hold).  ``Compound``
chains taskpools sequentially by completion callbacks.

``ParameterizedTaskpool`` is the engine behind the PTG front-end: its
startup hook enumerates the parameter space, counts local tasks, and
schedules dependency-free ones (reference: generated startup,
jdf2c.c:2989,4398).
"""

from __future__ import annotations

import itertools
import threading
from enum import IntEnum
from typing import Any, Callable, Dict, List, Optional, Sequence

from parsec_tpu.containers.hash_table import ConcurrentHashTable
from parsec_tpu.data.arena import Arena
from parsec_tpu.data.datarepo import DataRepo
from parsec_tpu.core.task import Task, TaskClass

_tp_ids = itertools.count(1)

_ndep_cls = None
_ndep_tried = False


def _native_dep_table():
    """A native dep-countdown table (schedext.DepTable) when the
    scheduler hot path is on and the extension builds, else None — the
    per-pool gate engine.deliver_dep dispatches on.  The class resolves
    once per process; the ``sched_native`` knob stays a live read so an
    A/B flip affects pools created after it."""
    global _ndep_cls, _ndep_tried
    from parsec_tpu.utils.mca import params
    if not int(params.get("sched_native", 1)):
        return None
    if not _ndep_tried:
        _ndep_tried = True
        from parsec_tpu.native import load_schedext
        se = load_schedext()
        if se is not None:
            _ndep_cls = se.DepTable
    return _ndep_cls() if _ndep_cls is not None else None


class TaskpoolState(IntEnum):
    CREATED = 0
    ATTACHED = 1
    RUNNING = 2
    DONE = 3


class Taskpool:
    """Base taskpool (reference: parsec_taskpool_t)."""

    #: dynamically-discovered pools count tasks into nb_tasks as they are
    #: instantiated (engine.deliver_dep) instead of at startup enumeration
    dynamic = False

    def __init__(self, name: str = "taskpool",
                 globals_: Optional[Dict[str, Any]] = None):
        self.taskpool_id = next(_tp_ids)
        self.name = name
        self.globals = dict(globals_ or {})
        self.context = None
        self.termdet = None
        self.state = TaskpoolState.CREATED
        self.nb_tasks = 0              # mutated only through termdet
        self.nb_pending_actions = 0    # idem
        #: name of the termdet module this pool wants instead of the
        #: context default (e.g. "user_trigger"; reference: DSLs install
        #: their own termdet before parsec_context_add_taskpool)
        self.termdet_name: Optional[str] = None
        self.task_classes: Dict[str, TaskClass] = {}
        self.arenas: Dict[str, Arena] = {}
        #: dep-countdown records for not-yet-ready tasks; the native
        #: twin (schedext.DepTable) replaces it wholesale when the
        #: scheduler hot path is on — ONE of the two holds this pool's
        #: records, selected once at construction (engine.deliver_dep)
        self.deps_table = ConcurrentHashTable()
        self._native_deps = _native_dep_table()
        #: collection datums whose host copy a writeback replaced; their
        #: user-visible backing re-links at termination (engine._writeback)
        self.dirty_data: set = set()
        #: reshape promises: one shared conversion per (copy, dtt) edge
        #: (reference: parsec_reshape.c promise table)
        from parsec_tpu.data.reshape import ReshapeCache
        self.reshape = ReshapeCache()
        #: extensible per-pool info slots (reference: the info object
        #: array hung off parsec_taskpool_t, class/info.h)
        from parsec_tpu.utils.info import InfoObjectArray, taskpool_info
        self.info = InfoObjectArray(taskpool_info, owner=self)
        self._complete_cbs: List[Callable[["Taskpool"], None]] = []
        self._done_event = threading.Event()
        #: pool-wide priority bias added to every task's priority — the
        #: job-service fairness lever: per-job priority rides into the
        #: priority schedulers (sched/local_queues pbq/ltq/lhq) so
        #: concurrent jobs interleave by weight instead of FIFO order
        self.priority = 0
        #: cancellation flag: workers discard (not execute) tasks of a
        #: cancelled pool, and the termdet clamps its counters at zero
        self.cancelled = False
        #: owning job id when enqueued through the job service (tags
        #: PINS events / per-job gauges); None for plain batch pools
        self.job_id: Optional[int] = None
        #: per-pool error route: when set, task errors of this pool go
        #: here instead of poisoning the whole context
        #: (``sink(exc, task)``; see Context.record_error)
        self.error_sink: Optional[Callable] = None
        #: ranks this pool exchanged traffic with (filled by the comm
        #: layer) — peer-death containment fails exactly the pools whose
        #: dataflow touches the dead rank (RemoteDepEngine._on_peer_dead)
        self.peer_ranks: set = set()
        #: recovery generation (core/recovery.py): bumped when a peer
        #: death restarts this pool on the survivors.  Tasks stamp it at
        #: construction (Task.pool_epoch); stale-generation tasks and
        #: counter decrements are fenced at task_progress /
        #: complete_execution, and cross-rank activations carry it so a
        #: survivor mid-restart parks frames from an already-recovered
        #: peer instead of losing them
        self.run_epoch = 0
        #: recovery spec: the collections this pool's dataflow reads and
        #: writes (builders set it; core/recovery.py snapshots/restores
        #: them) and, for insert-driven pools, a replay callable that
        #: re-inserts the lost work.  Empty/None = not recoverable —
        #: peer death keeps PR 5's containment behavior
        self.recovery_collections: list = []
        self.recovery_replay: Optional[Callable] = None
        #: recorded lineage log (core/recovery.LineageLog), installed by
        #: the RecoveryCoordinator at registration when the lineage
        #: plane is on.  None keeps complete_execution's hook at one
        #: attribute load + None check
        self._lineage = None
        #: minimal-replay enumeration filter (core/recovery.py): during
        #:  a minimal restart only keys in this set re-enumerate,
        #: re-deliver locally, and accept remote deliveries — every
        #: other delivery of the restarted generation is a redundant
        #: re-send of already-materialized work and drops.  None (the
        #: pristine and full-replay states) disables the gate
        self._replay_filter: Optional[set] = None
        #: GLOBALLY done: set once a distributed run passes global
        #: quiescence after this pool completed (Context.wait), or the
        #: recovery plane's RETIREMENT HANDSHAKE confirmed every live
        #: rank locally complete (core/recovery.py — the service-grade
        #: path for resident contexts that never call Context.wait).
        #: A pool that completed only LOCALLY stays restartable —
        #: another survivor may still need its re-executed partition;
        #: a retired one is never resurrected by recovery
        self.retired = False
        #: serving-fabric carve stamp (service/fabric.py): the memory-
        #: space indices this pool's tasks may execute on.  None =
        #: unrestricted (the whole warm mesh); a frozenset restricts
        #: DeviceRegistry.best_device to exactly those accelerator
        #: spaces, so concurrent tenants run on disjoint device subsets
        self.device_spaces: Optional[frozenset] = None

    # -- construction ------------------------------------------------------
    def add_task_class(self, tc: TaskClass) -> TaskClass:
        tc.task_class_id = len(self.task_classes)
        tc.taskpool = self
        tc.repo = DataRepo(nb_flows=len(tc.flows), name=tc.name)
        self.task_classes[tc.name] = tc
        return tc

    def add_arena(self, name: str, arena: Arena) -> None:
        self.arenas[name] = arena

    def on_complete(self, cb: Callable[["Taskpool"], None]) -> None:
        self._complete_cbs.append(cb)

    # -- lifecycle (driven by the Context) ---------------------------------
    def attach(self, context, termdet) -> None:
        """Install termination detection and take the startup hold
        (reference: parsec_context_add_taskpool, scheduling.c:692-697)."""
        self.context = context
        self.termdet = termdet
        termdet.monitor(self, self._terminated)
        # the pool holds one pending action until startup completed, so an
        # empty pool cannot terminate before being made ready
        termdet.taskpool_addto_runtime_actions(self, 1)
        self.state = TaskpoolState.ATTACHED

    def startup(self) -> List[Task]:
        """Produce the initial ready tasks; return them for scheduling.
        Subclasses implement enumeration; base pools start empty."""
        return []

    def ready(self) -> None:
        """Startup done: drop the hold and let termination fire
        (reference: parsec_taskpool_enable / termdet ready)."""
        self.state = TaskpoolState.RUNNING
        self.termdet.taskpool_ready(self)
        self.termdet.taskpool_addto_runtime_actions(self, -1)

    def _terminated(self) -> None:
        self.state = TaskpoolState.DONE
        for datum in self.dirty_data:
            if datum.collection is not None:
                datum.collection.refresh_backing(datum)
        self.dirty_data.clear()
        self.reshape.clear()
        cbs = list(self._complete_cbs)
        for cb in cbs:
            cb(self)
        if self.context is not None:
            self.context._taskpool_terminated(self)
        self._done_event.set()

    def cancel(self) -> None:
        """Cancel the pool: undelivered tasks are dropped at selection
        (scheduling.task_progress discards tasks of cancelled pools) and
        the termdet is force-quiesced so termination fires without the
        remaining counts draining naturally.  In-flight tasks finish
        their current execution; their late counter decrements clamp at
        zero (termdet tolerates cancelled pools).  Idempotent, callable
        from any thread."""
        self.cancelled = True
        if self.state == TaskpoolState.DONE:
            return
        if self.termdet is not None and self.state != TaskpoolState.CREATED:
            self.termdet.taskpool_force_quiesce(self)
        else:
            # never attached: nothing was scheduled, close out locally
            self.state = TaskpoolState.DONE
            self._done_event.set()

    def recovery_reset(self) -> None:
        """Drop every in-flight dependency/repo structure so the pool
        can re-enumerate from restored collection state (called by the
        RecoveryCoordinator AFTER the run_epoch bump fenced stale tasks
        and the termdet counters were rewound).  Subclasses with extra
        runtime state (DTD lanes/windows) extend this."""
        self.deps_table = ConcurrentHashTable()
        self._native_deps = _native_dep_table()
        for tc in self.task_classes.values():
            tc.repo = DataRepo(nb_flows=len(tc.flows), name=tc.name)
        self.reshape.clear()
        self.dirty_data.clear()
        self.peer_ranks = set()
        # the torn generation's lineage describes pre-restart state;
        # the new generation records afresh.  The replay filter is
        # (re)installed by the coordinator AFTER this reset when the
        # restart is minimal — None here is the full-replay default
        if self._lineage is not None:
            self._lineage.clear()
        self._replay_filter = None

    def wait_local(self, timeout: Optional[float] = None) -> bool:
        return self._done_event.wait(timeout)

    @property
    def completed(self) -> bool:
        return self.state == TaskpoolState.DONE

    def __repr__(self):
        return f"<Taskpool {self.name}#{self.taskpool_id} {self.state.name}>"


class ParameterizedTaskpool(Taskpool):
    """Taskpool whose DAG is a parameterized (problem-size-independent)
    graph — the PTG execution engine.  Each rank enumerates only its own
    tasks (owner computes)."""

    def startup(self) -> List[Task]:
        myrank = self.context.rank if self.context else 0
        nb_local = 0
        ready: List[Task] = []
        append = ready.append
        flt = self._replay_filter
        for tc in self.task_classes.values():
            aff = tc.affinity
            if aff is None and myrank != 0:
                continue   # rank_of is the constant 0: nothing local
            # classes with no task-fed inputs skip the per-instance
            # countdown probe entirely (class-level partition, task.py)
            all_ready = not tc._ft_inputs
            vt = tc.native_vt()
            if vt is not None and all_ready and aff is None \
                    and flt is None and tc.key_fn is None \
                    and len(tc.params) == 1:
                # flat dep-free class (the independent-task shape):
                # enumerate AND construct directly from the parameter
                # range in C — Python Task.__init__ and the per-
                # instance dict build leave the startup hot loop
                # entirely (schedext.TaskVT.build_range)
                space = tc.params[0][1](self.globals, {})
                if isinstance(space, range):
                    tasks = vt.build_range(tc.params[0][0], space.start,
                                           space.stop, space.step)
                else:
                    name = tc.params[0][0]
                    tasks = vt.build_batch([{name: v} for v in space])
                nb_local += len(tasks)
                ready.extend(tasks)
                continue
            build = vt.build_one if vt is not None else None
            for locals_ in tc.iter_space(self.globals):
                # owner-computes through the recovery translation: a
                # dead rank's partition enumerates on its adopting
                # survivor at re-execution (TaskClass.rank_of applies
                # the same table on the activation-routing side)
                if aff is not None and tc.rank_of(locals_) != myrank:
                    continue
                if flt is not None and tc.make_key(locals_) not in flt:
                    # minimal-replay restart: this task's outputs are
                    # intact and nothing in the plan consumes them —
                    # skip the re-execution entirely
                    continue
                nb_local += 1
                if all_ready or tc.nb_task_inputs(locals_) == 0:
                    # iter_space yields a fresh dict per instance, so
                    # the C constructor may alias it (build_one)
                    append(build(locals_) if build is not None
                           else Task(tc, self, locals_))
        if nb_local:
            self.termdet.taskpool_addto_nb_tasks(self, nb_local)
        return ready


class DynamicTaskpool(ParameterizedTaskpool):
    """Dynamically-discovered PTG pool (reference: ``%option dynamic``
    / ptgpp --dynamic-termdet, interfaces/ptg/ptg-compiler/main.c:28-44;
    the JDF customer is tests/apps/haar_tree/project_dyn.jdf): the
    parameter space is too large or unknowable to enumerate, so startup
    does NO enumeration — task classes carrying a ``startup_fn`` property
    seed the DAG (the reference's generated-startup replacement,
    project_dyn.jdf:109-159), every task discovered at runtime is counted
    into ``nb_tasks`` the moment it is instantiated (engine.deliver_dep),
    and termination fires when the in-flight count drains — dynamic
    termination detection.  Bodies may overwrite derived locals on
    ``task.locals`` (this_task->locals.X.value in the reference) to prune
    output guards at runtime."""

    dynamic = True

    def attach(self, context, termdet) -> None:
        super().attach(context, termdet)
        if context is not None and getattr(context, "comm", None) \
                is not None:
            # Distributed dynamic pools must NOT terminate on a local
            # zero count: a rank whose tasks all arrive by remote
            # discovery (the project_dyn seeding pattern) would fire
            # termination before the first activation lands, and a rank
            # that transiently drains to zero while a discovery message
            # is in flight would terminate early.  The reference needs a
            # DISTRIBUTED termdet for exactly this (ptgpp
            # --dynamic-termdet); here the pool takes a permanent
            # runtime-action hold, released only when the comm layer's
            # pool-scoped Safra round proves every rank drained with no
            # discovery in flight (RemoteDepEngine.resolve_dynamic_holds).
            self._dyn_hold = True
            termdet.taskpool_addto_runtime_actions(self, 1)
            context.comm.register_dynamic_hold(self)

    def startup(self) -> List[Task]:
        myrank = self.context.rank if self.context else 0
        ready: List[Task] = []
        for tc in self.task_classes.values():
            fn = tc.properties.get("startup_fn")
            if fn is None:
                continue
            for seed in fn(self.globals, myrank):
                locals_ = tc.complete_locals(dict(seed))
                ready.append(Task(tc, self, locals_))
        if ready:
            self.termdet.taskpool_addto_nb_tasks(self, len(ready))
        return ready


class Compound(Taskpool):
    """Sequential composition (reference: parsec_compose, compound.c):
    completion of pool N enqueues pool N+1."""

    def __init__(self, pools: Sequence[Taskpool], name: str = "compound"):
        super().__init__(name=name)
        self.pools = list(pools)
        self._idx = 0
        self._clock = threading.Lock()
        self._driving = False

    def attach(self, context, termdet) -> None:
        super().attach(context, termdet)
        # the compound holds one action per sub-pool still to run
        termdet.taskpool_addto_runtime_actions(self, len(self.pools))

    def startup(self) -> List[Task]:
        self._drive()
        return []

    def _drive(self) -> None:
        """Launch sub-pools iteratively.  Empty/instantly-completing pools
        fire their completion callback synchronously inside add_taskpool;
        the _driving flag turns that reentrancy into a loop iteration
        instead of recursion, so long compositions cannot overflow the
        stack."""
        while True:
            with self._clock:
                if self._driving or self._idx >= len(self.pools) \
                        or self.cancelled:
                    return
                self._driving = True
                launched = self._idx
                pool = self.pools[launched]
            pool.on_complete(self._sub_done)
            # recovery must never restart a compound member once it
            # completed: a re-fired completion would double-advance the
            # composition's cursor
            pool._compound_member = True
            self.context.add_taskpool(pool, start=True)
            # cancel() racing this launch saw the sub-pool CREATED and
            # skipped it; it set our flag BEFORE reading the state, so
            # re-checking after attach closes the window
            if self.cancelled and not pool.cancelled:
                pool.cancel()
            with self._clock:
                self._driving = False
                advanced = self._idx > launched
            if not advanced:
                return   # still running; its completion re-enters _drive

    def _sub_done(self, pool: Taskpool) -> None:
        with self._clock:
            self._idx += 1
            driving = self._driving
        self.termdet.taskpool_addto_runtime_actions(self, -1)
        if not driving:
            self._drive()

    def cancel(self) -> None:
        """Cancel the composition: the active sub-pool is cancelled,
        not-yet-launched sub-pools never start (_drive checks the flag),
        and the compound's own held actions are force-quiesced."""
        self.cancelled = True
        with self._clock:
            active = (self.pools[self._idx]
                      if self._idx < len(self.pools) else None)
        if active is not None and active.state not in (
                TaskpoolState.CREATED, TaskpoolState.DONE):
            active.cancel()
        super().cancel()


def compose(*pools: Taskpool) -> Compound:
    """parsec_compose equivalent; flattens nested compounds."""
    flat: List[Taskpool] = []
    for p in pools:
        if isinstance(p, Compound):
            flat.extend(p.pools)
        else:
            flat.append(p)
    return Compound(flat)
