"""Task model: task classes, flows, dependencies, task instances.

Rebuild of the reference's task-class vtable
(reference: parsec/parsec_internal.h:381-425 ``parsec_task_class_t``): a
TaskClass describes one parameterized family of tasks — its parameter
space, its data flows with guarded input/output dependencies, its affinity
(owner-computes placement), and its per-device-type incarnations (hooks).
A Task is one instantiation with concrete parameter values.

Dependency endpoints mirror the JDF notions (reference:
interfaces/ptg/ptg-compiler/jdf.h): a flow input comes from another task's
output flow, from the data collection (``A(k)``), from a fresh arena
allocation (NEW), or nowhere (NULL); outputs symmetrically go to successor
tasks and/or back to the collection.
"""

from __future__ import annotations

import itertools
from enum import IntEnum
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple, Union)

from parsec_tpu.data.data import (ACCESS_NONE, ACCESS_READ, ACCESS_RW,
                                  ACCESS_WRITE, DataCopy)
from parsec_tpu.data.collection import DataRef


class HookReturn(IntEnum):
    """Hook return codes (reference: parsec_hook_return_t)."""
    DONE = 0       # body executed, completion may proceed
    AGAIN = 1      # reschedule this task later (with fairness distance)
    ASYNC = 2      # device took ownership; completion arrives asynchronously
    NEXT = 3       # this incarnation declined; try the next chore
    DISABLE = 4    # disable this incarnation for the whole task class
    ERROR = -1


def normalize_body_outputs(ret: Any, writable: Sequence[str],
                           what: str = "body") -> Dict[str, Any]:
    """Normalize a functional body/kernel return value to {flow: value}.

    Shared by CPU bodies and device kernels so both incarnations of a task
    class follow one convention: a dict keyed by flow name, a tuple in
    written-flow declaration order, or a single value when exactly one
    flow is written.
    """
    if isinstance(ret, dict):
        return ret
    if isinstance(ret, (tuple, list)):
        if len(ret) != len(writable):
            raise ValueError(
                f"{what} returned {len(ret)} values for "
                f"{len(writable)} written flows {list(writable)}")
        return dict(zip(writable, ret))
    if len(writable) != 1:
        raise ValueError(
            f"{what} returned one value but writes {list(writable)}")
    return {writable[0]: ret}


# --------------------------------------------------------------------------
# Dependency endpoints
# --------------------------------------------------------------------------

class DepEnd:
    """Base endpoint of a dependency edge."""
    __slots__ = ()


class _TaskEnd(DepEnd):
    """Shared base of task-to-task endpoints.  ``params_fn`` may return a
    list of param dicts — the JDF range form (``-> TRSM(k+1..NT, k)`` /
    ``<- CTL First(0..3)``) — in which case the dep represents that many
    edges."""
    __slots__ = ("task_class", "flow", "params_fn")

    def __init__(self, task_class: str, flow: str,
                 params_fn: Callable[[Dict[str, int]], Any]):
        self.task_class = task_class
        self.flow = flow
        self.params_fn = params_fn

    def instances(self, locals_: Dict[str, int]) -> List[Dict[str, int]]:
        res = self.params_fn(locals_)
        return list(res) if isinstance(res, (list, tuple)) else [res]


class FromTask(_TaskEnd):
    """Input comes from task_class.flow of the instance(s) params_fn(locals)
    (reference: jdf dep ``A <- B TASK(k-1)``)."""
    __slots__ = ()


class ToTask(_TaskEnd):
    """Output feeds task_class.flow of the instance(s) params_fn(locals)."""
    __slots__ = ()


class FromDesc(DepEnd):
    """Input read directly from a data collection: ``<- A(k, n)``."""
    __slots__ = ("ref_fn",)

    def __init__(self, ref_fn: Callable[[Dict[str, int]], DataRef]):
        self.ref_fn = ref_fn


class ToDesc(DepEnd):
    """Output written back to the collection: ``-> A(k, n)``."""
    __slots__ = ("ref_fn",)

    def __init__(self, ref_fn: Callable[[Dict[str, int]], DataRef]):
        self.ref_fn = ref_fn


class New(DepEnd):
    """Input is a fresh arena allocation (JDF ``<- NEW``)."""
    __slots__ = ("arena_name",)

    def __init__(self, arena_name: str = "default"):
        self.arena_name = arena_name


class Null(DepEnd):
    """No data (JDF ``<- NULL`` / ``-> NULL``)."""
    __slots__ = ()


NULL = Null()


class Dep:
    """One guarded dependency (reference: jdf_dep_t with guard).

    ``guard(locals) -> bool`` decides applicability; ``end`` is the other
    endpoint; ``dtt`` optionally names the datatype/layout for reshapes;
    ``count(locals)`` is the edge multiplicity for gather deps — the JDF
    range form ``<- CTL First(0..3)`` is one dep representing 4 incoming
    edges, and the dep countdown must expect all of them.
    """
    __slots__ = ("guard", "end", "dtt", "count")

    def __init__(self, end: DepEnd,
                 guard: Optional[Callable[[Dict[str, int]], bool]] = None,
                 dtt: Any = None,
                 count: Optional[Callable[[Dict[str, int]], int]] = None):
        self.end = end
        self.guard = guard
        self.dtt = dtt
        self.count = count

    def applies(self, locals_: Dict[str, int]) -> bool:
        return True if self.guard is None else bool(self.guard(locals_))

    def multiplicity(self, locals_: Dict[str, int]) -> int:
        """Incoming-edge count: explicit ``count`` wins; a range FromTask
        contributes one edge per instance."""
        if self.count is not None:
            return int(self.count(locals_))
        if isinstance(self.end, FromTask):
            return len(self.end.instances(locals_))
        return 1


class Flow:
    """One named data flow of a task class (reference: parsec_flow_t)."""

    __slots__ = ("name", "access", "inputs", "outputs", "flow_index")

    def __init__(self, name: str, access: int,
                 inputs: Sequence[Dep] = (), outputs: Sequence[Dep] = ()):
        self.name = name
        self.access = access
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.flow_index = -1   # assigned by TaskClass

    def active_input(self, locals_: Dict[str, int]) -> Optional[Dep]:
        """The single input dep applying for these params (JDF semantics:
        guards are mutually exclusive)."""
        for dep in self.inputs:
            if dep.applies(locals_):
                return dep
        return None

    def active_outputs(self, locals_: Dict[str, int]) -> List[Dep]:
        return [dep for dep in self.outputs if dep.applies(locals_)]

    @property
    def is_ctl(self) -> bool:
        return self.access == ACCESS_NONE


def RW(name: str, inputs=(), outputs=()) -> Flow:
    return Flow(name, ACCESS_RW, inputs, outputs)


def READ(name: str, inputs=(), outputs=()) -> Flow:
    return Flow(name, ACCESS_READ, inputs, outputs)


def WRITE(name: str, inputs=(), outputs=()) -> Flow:
    return Flow(name, ACCESS_WRITE, inputs, outputs)


def CTL(name: str, inputs=(), outputs=()) -> Flow:
    return Flow(name, ACCESS_NONE, inputs, outputs)


# --------------------------------------------------------------------------
# Task class
# --------------------------------------------------------------------------

class TaskClass:
    """Parameterized task family (reference: parsec_task_class_t).

    ``params``: ordered (name, range_fn) pairs; range_fn(globals, locals)
    yields the values of that parameter given the outer ones — triangular
    spaces like ``m in k+1..NT`` fall out naturally.
    ``affinity``: locals -> DataRef; the task runs on rank_of that datum
    (owner computes, reference: jdf2c.c:2005 affinity generation).
    ``incarnations``: ordered (device_type, hook) preference list
    (reference: __parsec_chore_t).
    """

    def __init__(self, name: str,
                 params: Sequence[Tuple[str, Callable]] = (),
                 affinity: Optional[Callable[[Dict[str, int]], DataRef]] = None,
                 flows: Sequence[Flow] = (),
                 body: Optional[Callable] = None,
                 incarnations: Sequence[Tuple[str, Callable]] = (),
                 priority: Optional[Callable[[Dict[str, int]], int]] = None,
                 properties: Optional[Dict[str, Any]] = None,
                 key_fn: Optional[Callable[[Dict[str, int]], Any]] = None):
        self.name = name
        self.params = list(params)
        #: user-defined key function (reference: the [make_key_fn = ...]
        #: task-class property, tests/dsl/ptg/user-defined-functions/udf.jdf)
        self.key_fn = key_fn
        self.affinity = affinity
        self.flows = list(flows)
        for i, f in enumerate(self.flows):
            f.flow_index = i
        self._flow_by_name = {f.name: f for f in self.flows}
        # hot-path partitions, computed once per CLASS instead of
        # filtered per task instance (flows are fixed at construction;
        # the per-task loops in prepare_input / release_deps /
        # complete_execution walk only the flows that can matter)
        self._in_flows = [f for f in self.flows if f.inputs]
        self._noin_flow_names = [f.name for f in self.flows
                                 if not f.inputs]
        self._out_flows = [f for f in self.flows if f.outputs]
        self._write_flows = [f for f in self.flows
                             if f.access & ACCESS_WRITE]
        #: task-fed input deps only (the dep-countdown universe); an
        #: empty list makes nb_task_inputs O(1) — the dominant case for
        #: independent-task pools is "no task-fed inputs at all"
        self._ft_inputs = [d for f in self.flows for d in f.inputs
                           if isinstance(d.end, FromTask)]
        self._param_names = tuple(p for p, _ in self.params)
        self.incarnations = list(incarnations)
        if body is not None:
            self.incarnations.append(("cpu", body))
        self.chore_disabled_mask = 0   # class-wide disabled incarnations
        self.priority = priority
        self.properties = dict(properties or {})
        self.task_class_id = -1    # assigned by the taskpool
        self.repo = None           # DataRepo, created by the taskpool
        self.taskpool = None
        #: native per-class vtable (schedext.TaskVT): False = not yet
        #: resolved, None = native path off / extension missing
        self._vt = False

    def flow(self, name: str) -> Flow:
        return self._flow_by_name[name]

    # -- key machinery (reference: make_key / task_snprintf) --------------
    def make_key(self, locals_: Dict[str, int]) -> Tuple:
        if self.key_fn is not None:
            return (self.name, self.key_fn(locals_))
        # map + the C-level __getitem__ beats a genexpr at 100k keys/s
        return (self.name,) + tuple(map(locals_.__getitem__,
                                        self._param_names))

    def key_to_locals(self, key: Tuple) -> Dict[str, int]:
        return {p: key[1 + i] for i, (p, _) in enumerate(self.params)}

    def complete_locals(self, locals_: Dict[str, int]) -> Dict[str, int]:
        """Fill DERIVED parameters absent from a dep-provided params
        dict (single-value ranges over earlier params — the JDF
        derived-local idiom, e.g. the ring's visit class): dep
        expressions may name peers by the free parameters alone, but
        task instances carry the full local set.  A missing param whose
        range holds more than one value is a real addressing error."""
        if all(p in locals_ for p, _ in self.params):
            return locals_
        out = dict(locals_)
        g = self.taskpool.globals if self.taskpool is not None else {}
        for name, range_fn in self.params:
            if name in out:
                continue
            vals = list(range_fn(g, out))
            if len(vals) != 1:
                raise KeyError(
                    f"{self.name}: dep params missing {name!r}, which "
                    f"is not single-valued ({len(vals)} candidates)")
            out[name] = vals[0]
        return out

    # -- parameter space ---------------------------------------------------
    def iter_space(self, globals_: Dict[str, Any]) -> Iterable[Dict[str, int]]:
        """Enumerate the full parameter space (generated startup loops in the
        reference, jdf2c.c:2989)."""
        if len(self.params) == 1:
            # flat spaces (the independent-task shape) skip the
            # recursive generator: one dict literal per instance
            name, range_fn = self.params[0]
            for v in range_fn(globals_, {}):
                yield {name: v}
            return

        def rec(i: int, locals_: Dict[str, int]):
            if i == len(self.params):
                yield dict(locals_)
                return
            name, range_fn = self.params[i]
            for v in range_fn(globals_, locals_):
                locals_[name] = v
                yield from rec(i + 1, locals_)
                del locals_[name]
        yield from rec(0, {})

    def nb_task_inputs(self, locals_: Dict[str, int]) -> int:
        """How many incoming task-fed dep EDGES this instance has — the
        dep-countdown goal (reference: update_deps_with_counter counts every
        edge).  Data flows have mutually-exclusive guards (one source), but
        CTL flows may gather through several simultaneously-applying deps,
        and each counts."""
        deps = self._ft_inputs
        if not deps:
            return 0    # startup-enumeration fast path
        n = 0
        for dep in deps:
            if dep.applies(locals_):
                n += dep.multiplicity(locals_)
        return n

    # binding-table kinds, mirrored by native/schedext.c (CK_*)
    _CK_NULL, _CK_FROMDESC, _CK_NEW, _CK_FROMTASK, _CK_BAIL = 0, 1, 2, 3, 4
    _CK_TOTASK, _CK_OBAIL = 10, 11

    def _native_in_table(self):
        """Per-in-flow binding table for the C ``prepare_input`` twin:
        ``(flow_name, ((guard, kind, payload), ...))`` per flow, one
        entry per dep in declaration order (guards are mutually
        exclusive; the C plan picks the first applying one).  A dep the
        C chain cannot bind (reshape dtt, writeback, unknown end)
        becomes a BAIL entry — the instance pops back to Python."""
        table = []
        for flow in self._in_flows:
            deps = []
            for dep in flow.inputs:
                end = dep.end
                if isinstance(end, Null):
                    deps.append((dep.guard, self._CK_NULL, None))
                elif isinstance(end, FromDesc):
                    if dep.dtt is not None:   # converting read: reshape
                        deps.append((dep.guard, self._CK_BAIL, None))
                    else:
                        deps.append((dep.guard, self._CK_FROMDESC,
                                     end.ref_fn))
                elif isinstance(end, New):
                    deps.append((dep.guard, self._CK_NEW, end.arena_name))
                elif isinstance(end, FromTask):
                    # only reachable unbound (empty range -> None); the
                    # C side needs dep.multiplicity for the 0-edge test
                    deps.append((dep.guard, self._CK_FROMTASK, dep))
                else:
                    deps.append((dep.guard, self._CK_BAIL, None))
            table.append((flow.name, tuple(deps)))
        return tuple(table)

    def _native_out_table(self):
        """Per-out-flow delivery table for the C ``release_deps`` twin:
        ``(flow_name, flow_index, access, ((guard, kind, payload), ...))``
        with payload ``(end, succ_tc, succ_flow_name, succ_write)`` for
        local-capable ToTask deps.  Writebacks (ToDesc), reshaping edges
        (any dtt on either side), and unresolvable successors are BAIL
        entries; Null outputs deliver nothing and are omitted (exactly
        the Python walk's no-op arm)."""
        tp = self.taskpool
        table = []
        for flow in self._out_flows:
            deps = []
            for dep in flow.outputs:
                end = dep.end
                if isinstance(end, Null):
                    continue
                if not isinstance(end, ToTask):
                    deps.append((dep.guard, self._CK_OBAIL, None))
                    continue
                succ_tc = tp.task_classes.get(end.task_class) \
                    if tp is not None else None
                succ_flow = succ_tc._flow_by_name.get(end.flow) \
                    if succ_tc is not None else None
                if (succ_tc is None or succ_flow is None
                        or dep.dtt is not None
                        or any(d.dtt is not None
                               for d in succ_flow.inputs)):
                    deps.append((dep.guard, self._CK_OBAIL, None))
                    continue
                deps.append((dep.guard, self._CK_TOTASK,
                             (end, succ_tc, end.flow,
                              int(bool(succ_flow.access & ACCESS_WRITE)))))
            table.append((flow.name, flow.flow_index, flow.access,
                          tuple(deps)))
        return tuple(table)

    def native_vt(self):
        """The native per-class vtable (reference: the
        ``parsec_task_class_t`` vtable — schedext.TaskVT): C-side task
        construction for every class, plus the one-crossing progress
        chains — trivial (no flows) and extended (data-carrying classes
        via the binding tables above), both requiring a single cpu
        incarnation.  None when the native hot path is off or the
        extension did not build; resolved once per class (a class
        belongs to exactly one taskpool)."""
        vt = self._vt
        if vt is not False:
            return vt
        self._vt = None
        if self.taskpool is None:
            self._vt = False    # not attached yet: retry at next ask
            return None
        from parsec_tpu.utils.mca import params
        if not int(params.get("sched_native", 1)):
            return None
        from parsec_tpu.native import load_schedext
        se = load_schedext()
        if se is None or not hasattr(se, "TaskVT"):
            return None
        # drift guard: the C chain hardcodes the TaskStatus values
        if (int(TaskStatus.PENDING), int(TaskStatus.PREPARED),
                int(TaskStatus.RUNNING),
                int(TaskStatus.COMPLETE)) != (0, 2, 3, 4):
            raise RuntimeError(
                "TaskStatus drifted from schedext's hardcoded values")
        single_cpu = (len(self.incarnations) == 1
                      and self.incarnations[0][0] == "cpu"
                      and getattr(self.taskpool, "dynamic_release",
                                  None) is None)
        trivial = (single_cpu and not self._in_flows
                   and not self._out_flows and not self._write_flows)
        # extended chain: data-carrying class with a static binding
        # plan.  Dynamically-discovered (DTD) pools resolve successors
        # from their runtime graph, not from flow tables: Python only.
        cchain = (single_cpu and not trivial
                  and not getattr(self.taskpool, "dynamic", False)
                  and len(self.flows) <= 16)
        hook = self.incarnations[0][1] if (trivial or cchain) else None
        self._vt = se.TaskVT(self, self.taskpool, self.name,
                             self._param_names,
                             tuple(f.name for f in self.flows),
                             self.priority, self.key_fn, hook,
                             bool(trivial), int(bool(cchain)),
                             self._native_in_table() if cchain else (),
                             tuple(self._noin_flow_names)
                             if cchain else (),
                             self._native_out_table() if cchain else (),
                             tuple(f.name for f in self._write_flows)
                             if cchain else ())
        return self._vt

    def rank_of(self, locals_: Dict[str, int]) -> int:
        if self.affinity is None:
            return 0
        # owner_of, not rank_of: a dead rank's tasks place on the
        # survivor that adopted its partition of the affinity
        # collection (identity outside a recovery; collection.py)
        ref = self.affinity(locals_)
        return ref.dc.owner_of(*ref.indices)

    def __repr__(self):
        return f"<TaskClass {self.name}>"


# --------------------------------------------------------------------------
# Task instance
# --------------------------------------------------------------------------

class TaskStatus(IntEnum):
    PENDING = 0
    READY = 1
    PREPARED = 2
    RUNNING = 3
    COMPLETE = 4


_task_seq = itertools.count()


class Task:
    """One task instance (reference: parsec_task_t)."""

    __slots__ = ("task_class", "taskpool", "locals", "key", "priority",
                 "status", "data", "input_sources", "pinned_flows",
                 "chore_mask", "seq", "device", "prof", "dtd",
                 "ready_at", "mtr_t0", "retries", "retry_snap",
                 "pool_epoch")

    def __init__(self, task_class: TaskClass, taskpool, locals_: Dict[str, int]):
        self.task_class = task_class
        self.taskpool = taskpool
        self.locals = dict(locals_)
        self.key = task_class.make_key(self.locals)
        # class-level priority plus the pool-wide bias (Taskpool.priority;
        # the job service sets it per job so priority schedulers
        # interleave concurrent jobs by weight)
        self.priority = (task_class.priority(self.locals)
                         if task_class.priority else 0) \
            + getattr(taskpool, "priority", 0)
        self.status = TaskStatus.PENDING
        #: flow name -> DataCopy bound for this execution
        self.data: Dict[str, Optional[DataCopy]] = {}
        #: flow name -> (producer TaskClass, producer key) for repo release
        self.input_sources: Dict[str, Tuple[TaskClass, Tuple]] = {}
        #: task-fed flows: their bound copy is a version-pinned input that
        #: must never be superseded by a newer datum version at stage-in
        #: (reference: repo-pinned copies, datarepo.h:50-58)
        self.pinned_flows: set = set()
        self.chore_mask = 0xFFFF
        self.seq = next(_task_seq)
        self.device = None
        self.prof = None
        self.dtd = None     # DTD dep-bookkeeping state, if dynamically inserted
        #: perf_counter stamp of the moment the task became READY
        #: (schedule()); the causal tracer turns select - ready_at into
        #: the task's queue-wait span, and the metrics registry samples
        #: it into the queue-wait histogram.  None unless a telemetry
        #: consumer is installed (Context._ready_stamp)
        self.ready_at = None
        #: metrics sampling stamp (prof/metrics.py RuntimeMetrics):
        #: select-time perf_counter of a SAMPLED task; complete_exec
        #: closes it into the task-latency histogram
        self.mtr_t0 = None
        #: transient-failure retry bookkeeping (core/scheduling
        #: _maybe_retry; active only when task_retry_max > 0)
        self.retries = 0
        self.retry_snap = None
        #: the pool's recovery generation at construction: a restart
        #: bumps Taskpool.run_epoch, and every stale-generation task is
        #: discarded WITHOUT touching the re-counted termdet (the
        #: recovery fence; core/scheduling.py)
        self.pool_epoch = getattr(taskpool, "run_epoch", 0)

    def __repr__(self):
        args = ",".join(f"{k}={v}" for k, v in self.locals.items())
        return f"{self.task_class.name}({args})"
