"""Recursive device: task bodies that spawn inner taskpools.

Rebuild of the reference's recursive-call machinery (reference:
parsec/recursive.h:45 ``parsec_recursivecall`` + ``PARSEC_DEV_RECURSIVE``
device type, mca/device/device.h:64): a task body may decide its work is
better expressed as a whole task graph — e.g. factorizing one large tile
as a tiled algorithm over its sub-tiles — and hands the runtime an inner
taskpool.  The outer task completes when the inner pool does, re-entering
the normal release-deps path, so recursion nests to any depth.

Usage, from a CPU body that declared the ``es``/``task`` magic args::

    def body(T, es, task):
        sub = SubtileMatrix(task.data["T"].data, mb=inner_mb, nb=inner_mb)
        inner = potrf_taskpool(sub, device="tpu")
        return recursive_call(es, task, inner,
                              callback=lambda _t: sub.commit())

The callback runs on inner-pool completion BEFORE the outer task's deps
release (reference: parsec_recursivecall_callback, recursive.h:25) —
the place to ``SubtileMatrix.commit()`` the parent tile.
"""

from __future__ import annotations

from typing import Callable, Optional

from parsec_tpu.core.task import HookReturn, Task


def recursive_call(es, task: Task, inner_tp,
                   callback: Optional[Callable[[Task], None]] = None
                   ) -> HookReturn:
    """Enqueue ``inner_tp``; complete ``task`` on its completion.

    Returns ``HookReturn.ASYNC`` for the body to return: the runtime —
    not the body's return — completes the task (the same ownership
    contract as a device module; reference: PARSEC_HOOK_RETURN_ASYNC).
    """
    from parsec_tpu.core import scheduling
    ctx = es.context

    def _done(_inner):
        try:
            if callback is not None:
                callback(task)
        except Exception as exc:
            ctx.record_error(exc, task)
            scheduling.complete_execution(es, task, failed=True)
            return
        scheduling.complete_execution(es, task)

    inner_tp.on_complete(_done)
    ctx.add_taskpool(inner_tp, start=True)
    return HookReturn.ASYNC
