"""Hash-based irregular data distribution.

Reference: parsec/data_dist/hash_datadist.c — arbitrary key -> (rank, data)
mapping for irregular applications (trees, graphs, sparse problems).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from parsec_tpu.data.collection import DataCollection
from parsec_tpu.data.data import Data, new_data


class HashDatadist(DataCollection):
    def __init__(self, nodes: int = 1, myrank: int = 0, name: str = "H"):
        super().__init__(nodes=nodes, myrank=myrank, name=name)
        self._lock = threading.Lock()
        self._entries: Dict[Any, Tuple[int, int, Optional[Data]]] = {}

    def set_rank(self, key: Any, rank: int, vpid: int = 0) -> None:
        """Declare ownership of a key (all ranks declare the full map)."""
        with self._lock:
            old = self._entries.get(key)
            data = old[2] if old else None
            self._entries[key] = (rank, vpid, data)

    def set_data(self, key: Any, payload: np.ndarray) -> Data:
        """Attach the local payload for an owned key."""
        with self._lock:
            rank, vpid, _ = self._entries.get(key, (self.myrank, 0, None))
            d = new_data(payload, key=(self.name, key), collection=self)
            self._entries[key] = (rank, vpid, d)
            return d

    def data_key(self, key: Any) -> Any:
        return key

    def key_to_indices(self, key: Any) -> Tuple:
        return (key,)

    def rank_of(self, key: Any) -> int:
        with self._lock:
            e = self._entries.get(key)
        if e is None:
            raise KeyError(f"{self.name}: unknown key {key!r}")
        return e[0]

    def vpid_of(self, key: Any) -> int:
        with self._lock:
            e = self._entries.get(key)
        return e[1] if e else 0

    def data_of(self, key: Any) -> Data:
        with self._lock:
            e = self._entries.get(key)
        if e is None or e[2] is None:
            raise KeyError(f"{self.name}: no local data for {key!r}")
        return e[2]
