"""Data-collection base: the owner-computes mapping vtable.

Rebuild of the reference's data distribution base
(reference: include/parsec/data_distribution.h:26-66, data_distribution.c):
a collection maps a global key to ``rank_of`` (which process owns it),
``vpid_of`` (which NUMA domain / local partition), and ``data_of`` (the
local Data handle).  Task affinity follows these answers — that is the
distributed "owner computes" parallelism of the runtime, and on TPU the
same vtable additionally answers ``device_of`` so tiles pin to chips of the
mesh.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from parsec_tpu.data.data import Data


class DataCollection:
    """Abstract collection (reference: parsec_data_collection_t)."""

    #: recovery re-mapping (core/recovery.py): {dead rank -> adopting
    #: survivor} for THIS collection's partition, or None.  A class
    #: default keeps ``owner_of`` at one attribute load + None check
    #: when no recovery is active; installed per collection so pools
    #: over untouched collections never see a re-mapped owner.
    _recovery_translate = None
    #: re-runnable source: ``fn(*indices) -> ndarray`` regenerating a
    #: tile's INITIAL payload — the lineage walk's base version for
    #: tiles whose live copies died with their rank (the "re-runnable
    #: source task" of the recovery plane; see core/recovery.py)
    init_fn = None

    def __init__(self, nodes: int = 1, myrank: int = 0, name: str = "dc"):
        self.nodes = nodes
        self.myrank = myrank
        self.name = name
        self.dc_id = None        # registered id (taskpool serialization)

    # -- the vtable -------------------------------------------------------
    def data_key(self, *indices) -> Any:
        """Flatten index tuple to a canonical key."""
        raise NotImplementedError

    def rank_of(self, *indices) -> int:
        raise NotImplementedError

    def owner_of(self, *indices) -> int:
        """The rank currently SERVING these indices: ``rank_of`` routed
        through the recovery translation (a dead rank's partition is
        re-balanced onto survivors; core/recovery.py).  Runtime rank
        decisions — task placement, activation routing, local-tile
        materialization — go through here; ``rank_of`` stays the pure
        distribution function."""
        r = self.rank_of(*indices)
        t = self._recovery_translate
        return t.get(r, r) if t else r

    def set_init(self, fn) -> "DataCollection":
        """Register a re-runnable tile source: ``fn(*indices)`` returns
        the INITIAL payload of a tile.  Recovery reconstructs a dead
        rank's lost tiles from it when no snapshot survives."""
        self.init_fn = fn
        return self

    def set_rank_translation(self, table: Optional[Dict[int, int]]) -> None:
        """Install (or clear, with None/{}) the recovery re-mapping for
        this collection.  Written by the RecoveryCoordinator only."""
        self._recovery_translate = dict(table) if table else None

    def tile_key(self, *indices) -> tuple:
        """The LINEAGE identity of one tile: the key its ``Data`` is
        created with and the key the recovery lineage log records for
        reads/writes (core/recovery.py) — one source of truth, so the
        minimal-replay planner can map a recorded tile back to
        ``(collection, indices)`` without guessing the construction."""
        return (self.name,) + tuple(indices)

    def vpid_of(self, *indices) -> int:
        return 0

    def data_of(self, *indices) -> Data:
        """The local Data for these indices (only valid on the owner rank)."""
        raise NotImplementedError

    def rank_of_key(self, key: Any) -> int:
        return self.rank_of(*self.key_to_indices(key))

    def data_of_key(self, key: Any) -> Data:
        return self.data_of(*self.key_to_indices(key))

    def key_to_indices(self, key: Any) -> Tuple:
        raise NotImplementedError

    def refresh_backing(self, datum: Data) -> None:
        """Re-link a datum whose host copy was detached from user-visible
        backing storage (no-op for collections without a backing array)."""

    # -- convenience ------------------------------------------------------
    @property
    def super(self):
        """C struct-embedding shim: the reference reaches the tiled-matrix
        base as ``desc->super`` and the collection as ``desc->super.super``
        (two_dim_rectangle_cyclic.h:24); Python flattens the embedding, so
        the chain terminates on the object itself — JDF expressions like
        ``dA->super.mt`` (kcyclic.jdf:111) read through unchanged."""
        return self

    def is_local(self, *indices) -> bool:
        return self.owner_of(*indices) == self.myrank

    def __call__(self, *indices) -> "DataRef":
        """``A(k)`` in flow specifications resolves through here."""
        return DataRef(self, indices)


class DataRef:
    """A symbolic reference to a collection element (``A(m, n)``), used by
    flow endpoint expressions before resolution."""

    __slots__ = ("dc", "indices")

    def __init__(self, dc: DataCollection, indices: Tuple):
        self.dc = dc
        self.indices = indices

    @property
    def rank(self) -> int:
        return self.dc.rank_of(*self.indices)

    def resolve(self) -> Data:
        return self.dc.data_of(*self.indices)

    def __repr__(self):
        return f"{self.dc.name}{self.indices}"


_dc_registry_lock = threading.Lock()
_dc_registry: Dict[int, DataCollection] = {}
_dc_next_id = [1]


def dc_register(dc: DataCollection) -> int:
    """Register for cross-rank identification
    (reference: parsec_dc_register_id)."""
    with _dc_registry_lock:
        dc_id = _dc_next_id[0]
        _dc_next_id[0] += 1
        dc.dc_id = dc_id
        _dc_registry[dc_id] = dc
        return dc_id


def dc_lookup(dc_id: int) -> Optional[DataCollection]:
    with _dc_registry_lock:
        return _dc_registry.get(dc_id)


def dc_unregister(dc_id: int) -> None:
    with _dc_registry_lock:
        dc = _dc_registry.pop(dc_id, None)
        if dc is not None:
            dc.dc_id = None
