"""Recursive sub-tiling of a single tile.

Reference: parsec/data_dist/matrix/subtile.c — wraps one tile of a parent
collection as its own tiled matrix so hierarchical/recursive algorithms
(the recursive device, SURVEY.md §2.3) can run an inner taskpool on it.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from parsec_tpu.data.data import ACCESS_RW, Data
from parsec_tpu.data.matrix import TiledMatrix


class SubtileMatrix(TiledMatrix):
    """View one parent tile as an mb x nb tiled matrix (always rank-local).

    Construction claims the parent for host-side read-write: the newest
    copy is pulled home first (it may be device-resident) and other copies
    are invalidated, so the inner taskpool's in-place writes through the
    backing views cannot be shadowed by a stale device copy.  Call
    ``commit()`` when the inner taskpool completes to version-bump the
    parent (the recursive-completion hook does this).
    """

    def __init__(self, parent_tile: Data, mb: int, nb: int, name: str = "sub"):
        copy = parent_tile.pull_to_host()
        if copy is None or copy.payload is None:
            raise ValueError("parent tile has no materialized copy")
        parent_tile.transfer_ownership(0, ACCESS_RW)
        a = np.asarray(copy.payload)
        super().__init__(mb, nb, a.shape[0], a.shape[1], dtype=a.dtype,
                         nodes=1, myrank=0, name=name)
        self.parent = parent_tile
        self.from_array(a)

    def commit(self) -> None:
        """Publish the inner writes: bump the parent's host version."""
        self.parent.complete_write(0)

    def rank_of(self, m: int, n: int = 0) -> int:
        return 0
