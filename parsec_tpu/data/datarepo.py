"""Data repositories: hashed storage of completed-task outputs.

Rebuild of the reference's data repo (reference: parsec/datarepo.{c,h}):
each task class has a repo hashing its completed tasks' output copies by
task key.  Successors look entries up and consume them; an entry retires
(releasing its copies) when every registered consumer has used it — the
usage-count/retirement protocol of datarepo.h:50-58, whose lifetime rules
the dep engine must follow exactly to avoid leaks and use-after-free.

All usage-count mutations happen under the hash table's bucket lock
(ConcurrentHashTable.mutate), so an entry whose count reaches zero is
removed in the same critical section — no revival race between a retiring
consumer and a concurrent lookup_entry_and_create.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from parsec_tpu.containers.hash_table import REMOVE, ConcurrentHashTable


class RepoEntry:
    __slots__ = ("key", "copies", "usage", "on_retire")

    def __init__(self, key: Any, nb_flows: int):
        self.key = key
        self.copies: List[Optional[Any]] = [None] * nb_flows
        self.usage = 0        # mutated only under the repo's bucket lock
        self.on_retire: Optional[Callable[["RepoEntry"], None]] = None


class DataRepo:
    """Per-task-class repo (reference: data_repo_t)."""

    def __init__(self, nb_flows: int, name: str = ""):
        self.nb_flows = nb_flows
        self.name = name
        self._table = ConcurrentHashTable()

    def lookup_entry(self, key: Any) -> Optional[RepoEntry]:
        return self._table.find(key)

    def lookup_entry_and_create(self, key: Any) -> RepoEntry:
        """Find or atomically create the entry for ``key``, taking a usage
        hold so it cannot retire under the caller
        (reference: data_repo_lookup_entry_and_create)."""
        def fn(cur):
            e = cur if cur is not None else RepoEntry(key, self.nb_flows)
            e.usage += 1
            return e, e
        return self._table.mutate(key, fn)

    def _addto_usage(self, key: Any, delta: int) -> Optional[RepoEntry]:
        """Adjust usage; atomically remove on zero. Returns the entry to
        retire (caller fires on_retire outside the lock) or None."""
        def fn(cur):
            if cur is None:
                raise KeyError(f"repo {self.name}: no entry {key}")
            cur.usage += delta
            if cur.usage == 0:
                return REMOVE, cur
            return cur, None
        entry = self._table.mutate(key, fn)
        if entry is not None and entry.on_retire is not None:
            entry.on_retire(entry)
        return entry

    def entry_addto_usage_limit(self, key: Any, nb_usage: int) -> None:
        """Producer declares how many consumers will use the entry and drops
        its creation hold (reference: data_repo_entry_addto_usage_limit)."""
        self._addto_usage(key, nb_usage - 1)

    def entry_used_once(self, key: Any) -> None:
        """One consumer is done (reference: data_repo_entry_used_once)."""
        self._addto_usage(key, -1)

    def __len__(self) -> int:
        return len(self._table)
