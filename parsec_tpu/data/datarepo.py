"""Data repositories: hashed storage of completed-task outputs.

Rebuild of the reference's data repo (reference: parsec/datarepo.{c,h}):
each task class has a repo hashing its completed tasks' output copies by
task key.  Successors look entries up and consume them; an entry retires
(releasing its copies) when every registered consumer has used it — the
usage-count/retirement protocol of datarepo.h:50-58, whose lifetime rules
the dep engine must follow exactly to avoid leaks and use-after-free.

Like the reference, an entry carries a usage *limit* (declared by the
producer once it knows its consumer count) and a usage *count* (incremented
by consumers); retirement requires BOTH that the limit was declared and
that the count reached it — consumers racing ahead of the producer's
declaration can never retire the entry early.  All mutations ride the hash
table's bucket locks (ConcurrentHashTable.mutate) so retire-vs-revive races
are structurally impossible.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from parsec_tpu.containers.hash_table import REMOVE, ConcurrentHashTable


class RepoEntry:
    __slots__ = ("key", "copies", "usagelmt", "usagecnt", "declared",
                 "on_retire")

    def __init__(self, key: Any, nb_flows: int):
        self.key = key
        self.copies: List[Optional[Any]] = [None] * nb_flows
        self.usagelmt = 0      # mutated only under the repo's bucket lock
        self.usagecnt = 0      # idem
        self.declared = False  # producer has set the limit
        self.on_retire: Optional[Callable[["RepoEntry"], None]] = None


class DataRepo:
    """Per-task-class repo (reference: data_repo_t)."""

    def __init__(self, nb_flows: int, name: str = ""):
        self.nb_flows = nb_flows
        self.name = name
        self._table = ConcurrentHashTable()

    def lookup_entry(self, key: Any) -> Optional[RepoEntry]:
        return self._table.find(key)

    def lookup_entry_and_create(self, key: Any) -> RepoEntry:
        """Find or atomically create the entry for ``key``
        (reference: data_repo_lookup_entry_and_create).  The entry cannot
        retire before the producer declares its usage limit."""
        def fn(cur):
            e = cur if cur is not None else RepoEntry(key, self.nb_flows)
            return e, e
        return self._table.mutate(key, fn)

    def _mutate_counts(self, key: Any, fn_counts) -> None:
        def fn(cur):
            if cur is None:
                raise KeyError(f"repo {self.name}: no entry {key}")
            fn_counts(cur)
            if cur.declared and cur.usagecnt >= cur.usagelmt:
                return REMOVE, cur
            return cur, None
        entry = self._table.mutate(key, fn)
        if entry is not None and entry.on_retire is not None:
            entry.on_retire(entry)

    def entry_addto_usage_limit(self, key: Any, nb_usage: int) -> None:
        """Producer declares how many consumptions will occur
        (reference: data_repo_entry_addto_usage_limit)."""
        def bump(e):
            e.usagelmt += nb_usage
            e.declared = True
        self._mutate_counts(key, bump)

    def entry_used_once(self, key: Any) -> None:
        """One consumer is done (reference: data_repo_entry_used_once)."""
        def bump(e):
            e.usagecnt += 1
        self._mutate_counts(key, bump)

    def __len__(self) -> int:
        return len(self._table)
