"""Map / apply / reduce operator taskpools over tiled matrices.

Rebuild of the reference's collection operators
(reference: parsec/data_dist/matrix/map_operator.c, apply_wrapper.c,
reduce_wrapper.c): generic taskpools applying a user operator to every
tile, mapping one collection onto another, and reducing all tiles through
a binary combination tree.  Built on the PTG front-end, so they inherit
owner-computes placement and run on any scheduler/device.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import numpy as np

from parsec_tpu.core.taskpool import ParameterizedTaskpool
from parsec_tpu.data.matrix import TiledMatrix
from parsec_tpu.dsl.ptg import DATA, IN, NEW, OUT, PTG, Range, TASK


def apply_op(A: TiledMatrix, op: Callable[[np.ndarray, int, int], Any],
             name: str = "apply") -> ParameterizedTaskpool:
    """In-place ``op(tile, m, n)`` on every stored tile
    (reference: parsec_apply / apply_wrapper.c)."""
    g = PTG(name)
    g.task("APPLY", m=Range(0, A.mt - 1), n=Range(0, A.nt - 1)) \
     .affinity(lambda m, n: A(m, n)) \
     .flow("T", "RW",
           IN(DATA(lambda m, n: A(m, n))),
           OUT(DATA(lambda m, n: A(m, n)))) \
     .body(lambda T, m, n: (op(T, m, n), None)[1])
    tp = g.build()
    if hasattr(A, "tile_exists"):
        tc = tp.task_classes["APPLY"]
        orig = tc.iter_space

        def filtered(globals_):
            for loc in orig(globals_):
                if A.tile_exists(loc["m"], loc["n"]):
                    yield loc
        tc.iter_space = filtered
    return tp


def map_op(A: TiledMatrix, B: TiledMatrix,
           op: Callable[[np.ndarray, np.ndarray, int, int], Any],
           name: str = "map") -> ParameterizedTaskpool:
    """``op(a_tile, b_tile, m, n)`` reading A, writing B
    (reference: map_operator.c).  A and B must be tiled identically."""
    if (A.mt, A.nt) != (B.mt, B.nt):
        raise ValueError("map_op requires identical tilings")
    g = PTG(name)
    g.task("MAP", m=Range(0, A.mt - 1), n=Range(0, A.nt - 1)) \
     .affinity(lambda m, n: B(m, n)) \
     .flow("X", "READ", IN(DATA(lambda m, n: A(m, n)))) \
     .flow("Y", "RW",
           IN(DATA(lambda m, n: B(m, n))),
           OUT(DATA(lambda m, n: B(m, n)))) \
     .body(lambda X, Y, m, n: (op(X, Y, m, n), None)[1])
    return g.build()


def reduce_op(A: TiledMatrix,
              op: Callable[[np.ndarray, np.ndarray], np.ndarray],
              result: Optional[Dict[str, Any]] = None,
              name: str = "reduce"):
    """Binary-tree reduction of all tiles with ``op(acc, tile) -> acc``
    (reference: reduce_wrapper.c binary reduction col/row).

    Returns (taskpool, result_holder); after the pool completes,
    ``result_holder["value"]`` is the tile-shaped reduction of all tiles.
    Requires uniform tile shapes (lm % mb == 0 and ln % nb == 0).
    """
    if A.lm % A.mb or A.ln % A.nb:
        raise ValueError("reduce_op requires uniform (full) tiles")
    tiles = [(m, n) for m in range(A.mt) for n in range(A.nt)]
    T = len(tiles)
    holder = result if result is not None else {}
    if T == 0:
        holder["value"] = None
        return PTG(name).build(), holder
    L = max(1, math.ceil(math.log2(T))) if T > 1 else 1
    counts = {0: T}
    for lvl in range(1, L + 1):
        counts[lvl] = -(-counts[lvl - 1] // 2)

    def child_exists(l, i):
        return 2 * i + 1 < counts[l - 1]

    def tile_ref(i):
        return A(*tiles[i])

    g = PTG(name, L=L)
    tb = g.task("RED", l=Range(1, L),
                i=Range(0, lambda l: counts[l] - 1))
    # keep the whole tree on tile 0's rank — reductions are latency-bound;
    # smarter placement lands with the comm layer
    tb.affinity(lambda l, i: A(*tiles[0]))
    tb.flow("X", "READ",
            IN(DATA(lambda i: tile_ref(2 * i)), when=lambda l: l == 1),
            IN(TASK("RED", "P", lambda l, i: dict(l=l - 1, i=2 * i)),
               when=lambda l: l > 1))
    tb.flow("Y", "READ",
            IN(DATA(lambda i: tile_ref(2 * i + 1)),
               when=lambda l, i: l == 1 and child_exists(1, i)),
            IN(TASK("RED", "P", lambda l, i: dict(l=l - 1, i=2 * i + 1)),
               when=lambda l, i: l > 1 and child_exists(l, i)))
    tb.flow("P", "WRITE",
            IN(NEW("acc")),
            OUT(TASK("RED", "X", lambda l, i: dict(l=l + 1, i=i // 2)),
                when=lambda l, i, L=L: l < L and i % 2 == 0),
            OUT(TASK("RED", "Y", lambda l, i: dict(l=l + 1, i=i // 2)),
                when=lambda l, i, L=L: l < L and i % 2 == 1))

    def body(X, Y, P, l, i, L=L):
        acc = np.array(X, copy=True) if Y is None else op(X, Y)
        P[...] = acc
        if l == L:
            holder["value"] = np.array(P, copy=True)

    tb.body(body)
    g.arena("acc", (A.mb, A.nb), A.dtype)
    return g.build(), holder
