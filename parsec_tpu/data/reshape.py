"""Reshape engine: datatype/layout conversion on dependency edges.

Rebuild of the reference's reshape machinery (reference:
parsec/parsec_reshape.c — ``parsec_local_reshape``,
``parsec_get_copy_reshape_from_{desc,dep}`` parsec_internal.h:617-634,
``parsec_set_up_reshape_promise`` :606): when a dependency edge carries a
datatype tag (``dtt``) different from the produced copy's type, the
consumer receives a converted copy materialized through a shared
datacopy-future promise — one conversion feeds every consumer demanding
the same dtt, and the converted copy is released when the last of them
consumed it.

On TPU a "datatype" is (dtype, layout transform): the canonical uses are
precision staging (f32 collections with bf16 compute edges — the MXU-
native mixed precision) and relayout (transpose/retile) on an edge.
Conversions of device-resident payloads run as jitted XLA programs on the
owning device (no host round-trip); host payloads convert in numpy.

Edge semantics (mirroring the reference's reshape test matrix,
tests/collections/reshape/):
- IN(TASK(...), dtt=t)   — consumer-side reshape of a task-fed edge
- IN(DATA(...), dtt=t)   — reshape on read from the collection
- OUT(DATA(...), dtt=t)  — reshape on write-back home (the inverse
                           transform, then cast to the collection dtype)
- remote edges           — pre-send reshape: the converted payload is
                           what travels (remote_dep.flush_activations)
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from parsec_tpu.containers.futures import DataCopyFuture
from parsec_tpu.data.data import Coherency, Data, DataCopy


class Dtt:
    """A datatype/layout tag for dependency edges
    (reference: parsec_arena_datatype_t + MPI datatype on a dep,
    parsec_internal.h:41-45)."""

    __slots__ = ("name", "dtype", "transform", "inverse")

    def __init__(self, dtype: Any = None,
                 transform: Optional[Callable] = None,
                 inverse: Optional[Callable] = None,
                 name: Optional[str] = None):
        self.dtype = np.dtype(dtype) if dtype is not None else None
        self.transform = transform
        self.inverse = inverse
        self.name = name or (self.dtype.name if self.dtype is not None
                             else f"dtt@{id(self):x}")

    def key(self) -> Tuple:
        return (self.name, str(self.dtype),
                id(self.transform) if self.transform else 0)

    def __repr__(self):
        return f"<Dtt {self.name}>"


def as_dtt(spec: Any) -> Optional["Dtt"]:
    """Coerce a user-facing dtt spec: Dtt | dtype-like | None."""
    if spec is None or isinstance(spec, Dtt):
        return spec
    return Dtt(dtype=spec)


def _is_device_array(payload) -> bool:
    return payload is not None and not isinstance(payload, np.ndarray) \
        and hasattr(payload, "devices")


def convert(payload, dtt: Dtt, inverse: bool = False):
    """Apply a dtt to a payload.  Device arrays convert on-device (XLA
    fuses the cast/relayout into one program); host arrays via numpy."""
    fn = dtt.inverse if inverse else dtt.transform
    if _is_device_array(payload):
        import jax.numpy as jnp
        arr = payload
        if fn is not None:
            arr = fn(arr)
        if dtt.dtype is not None and not inverse:
            arr = arr.astype(dtt.dtype)
        return arr
    arr = np.asarray(payload)
    if fn is not None:
        arr = np.asarray(fn(arr))
    if dtt.dtype is not None and not inverse:
        arr = arr.astype(dtt.dtype)
    return arr


def needs_reshape(copy: DataCopy, dtt: Optional[Dtt]) -> bool:
    if dtt is None or copy is None or copy.payload is None:
        return False
    if dtt.transform is not None:
        return True
    if dtt.dtype is None:
        return False
    have = getattr(copy.payload, "dtype", None)
    return have is None or np.dtype(have) != dtt.dtype


class ReshapeCache:
    """Per-taskpool table of reshape promises
    (reference: the reshape repo keyed by (entry, dep datatype),
    parsec_reshape.c).  One DataCopyFuture per (source copy, dtt): every
    consumer demanding the same conversion shares one materialization.
    """

    def __init__(self):
        self._futures: Dict[Tuple, DataCopyFuture] = {}
        self._lock = threading.Lock()
        #: keys whose converted copy died; the GC-triggered weakref
        #: callback must NOT take _lock (the cycle collector can run
        #: while this thread already holds it), so it only appends here
        #: (list.append is atomic) and lookups drain the list under lock
        self._dead: list = []
        self.conversions = 0   # completed materializations (stats/tests)

    def _drain_dead_locked(self) -> None:
        while self._dead:
            key = self._dead.pop()
            ent = self._futures.get(key)
            if isinstance(ent, tuple) and ent[0]() is None:
                del self._futures[key]

    def get_copy(self, copy: DataCopy, dtt: Dtt) -> DataCopy:
        """The converted counterpart of ``copy`` under ``dtt``.

        Lifetime: once materialized, the table keeps only WEAK references
        — consumers hold the converted copy through their task bindings,
        so the cache must not pin it (nor the source copy, which the
        pending future's trigger closure holds) for the pool's lifetime
        (reference: reshape promises are freed when the last consumer
        used them, parsec_reshape.c / datacopy-future cleanup).  A later
        consumer of the same (source, dtt) either hits the still-live
        converted copy — identity-checked against the source to rule out
        id() reuse — or pays a re-conversion."""
        if not needs_reshape(copy, dtt):
            return copy
        key = (id(copy), copy.version, dtt.key())
        with self._lock:
            self._drain_dead_locked()
            ent = self._futures.get(key)
            if isinstance(ent, tuple):          # (weak dc, weak src)
                dc, src = ent[0](), ent[1]()
                if dc is not None and src is copy:
                    return dc
                ent = None
            if ent is None:
                def trigger(_spec, copy=copy, dtt=dtt):
                    self.conversions += 1
                    arr = convert(copy.payload, dtt)
                    datum = Data(nb_elts=getattr(arr, "nbytes", 0))
                    device = copy.device if _is_device_array(arr) else 0
                    dc = DataCopy(datum, device, payload=arr,
                                  coherency=Coherency.SHARED,
                                  version=copy.version)
                    dc.dtt = dtt
                    datum.attach_copy(dc)
                    return dc
                ent = DataCopyFuture(trigger)
                self._futures[key] = ent
            fut = ent
        dc = fut.get_copy()

        def prune(_ref, key=key):
            self._dead.append(key)   # lock-free; drained under _lock

        with self._lock:
            if self._futures.get(key) is fut:
                # materialized: drop the future and its source pin; the
                # weakref callback queues the dead entry for pruning so
                # the table does not grow one tombstone per conversion
                self._futures[key] = (weakref.ref(dc, prune),
                                      weakref.ref(copy))
        return dc

    def clear(self) -> None:
        with self._lock:
            self._futures.clear()
            self._dead.clear()
