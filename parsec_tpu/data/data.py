"""Canonical data + per-memory-space copies with coherency.

Rebuild of the reference's data substrate (reference: parsec/data.c,
parsec/data_internal.h:35-81, parsec/data.h:28-31): a ``Data`` is one logical
datum (a matrix tile, say); it owns ``DataCopy`` instances, one per memory
space holding a version of the payload.  Coherency follows the reference's
MOESI-flavored protocol:

    INVALID    copy exists but its payload is stale
    SHARED     valid for reading; other valid copies may exist
    OWNED      valid, authoritative; other SHARED copies may exist
    EXCLUSIVE  valid and the only valid copy (a write makes it so)

On TPU, memory space 0 is host RAM (numpy payloads) and spaces >=1 are
device HBM (jax.Array payloads); actual movement is delegated to the device
layer's transfer hooks, so this module stays device-agnostic.
"""

from __future__ import annotations

import itertools
import threading
from enum import IntEnum
from typing import Any, Dict, Optional

# Flow access modes (reference: parsec/flow modes FLOW_ACCESS_*)
ACCESS_NONE = 0x0
ACCESS_READ = 0x1
ACCESS_WRITE = 0x2
ACCESS_RW = ACCESS_READ | ACCESS_WRITE

# DataCopy.flags bits
FLAG_COW = 0x1   # payload is shared with readers: duplicate before writing
FLAG_SCRATCH = 0x2   # NEW-flow arena buffer: content undefined until the
                     # first writer runs (device stage-in may materialize
                     # it on device instead of shipping host bytes)


class Coherency(IntEnum):
    INVALID = 0
    OWNED = 1
    EXCLUSIVE = 2
    SHARED = 4


_data_keygen = itertools.count()


class DataCopy:
    """One version of a datum in one memory space
    (reference: parsec_data_copy_t)."""

    __slots__ = ("data", "device", "payload", "coherency", "version",
                 "readers", "flags", "arena", "arena_refs", "dtt",
                 "__weakref__")

    def __init__(self, data: "Data", device: int, payload: Any = None,
                 coherency: Coherency = Coherency.INVALID, version: int = 0):
        self.data = data
        self.device = device
        self.payload = payload
        self.coherency = coherency
        self.version = version
        self.readers = 0          # active reader count (stage-out gating)
        self.flags = 0
        self.arena = None         # owning arena, if arena-allocated
        #: repo-entry holds on an arena copy: a NEW-flow buffer chained
        #: through several tasks is registered in EVERY producer's repo
        #: entry, and may only return to the freelist when the LAST
        #: entry retires (reference: refcounted copies in repo entries,
        #: datarepo.h:50-58)
        self.arena_refs = 0
        self.dtt = None           # datatype/layout tag (reshape engine)

    def is_pinned_snapshot(self, pinned: bool) -> bool:
        """True when this bound copy must be read as a version-pinned
        snapshot rather than through the datum's coherency protocol:
        either a writeback replacement detached it, or — for a task-fed
        (pinned) input — a concurrent writeback invalidated it in place.
        (A detached copy with payload None was merely evicted and should
        re-stage from the datum's newest valid copy instead.)"""
        if self.payload is None or self.data is None:
            return False
        attached = self.data.copy_on(self.device) is self
        return (not attached) or \
            (pinned and self.coherency == Coherency.INVALID)

    def __repr__(self):
        return (f"<DataCopy dev={self.device} v={self.version} "
                f"{self.coherency.name} of {self.data}>")


class Data:
    """One logical datum with per-device copies (reference: parsec_data_t)."""

    def __init__(self, key: Any = None, collection: Any = None,
                 nb_elts: int = 0, owner_device: int = 0):
        self.key = key if key is not None else next(_data_keygen)
        self.collection = collection
        self.nb_elts = nb_elts
        self.owner_device = owner_device
        self.preferred_device = -1
        self._lock = threading.RLock()
        self._copies: Dict[int, DataCopy] = {}
        self._version_clock = 0   # monotonic; never regresses on invalidation

    def __repr__(self):
        return f"<Data key={self.key}>"

    # -- copy management -------------------------------------------------
    def attach_copy(self, copy: DataCopy) -> DataCopy:
        with self._lock:
            if copy.device in self._copies:
                raise ValueError(f"device {copy.device} already has a copy")
            self._copies[copy.device] = copy
            self._version_clock = max(self._version_clock, copy.version)
            return copy

    def detach_copy(self, device: int) -> Optional[DataCopy]:
        with self._lock:
            return self._copies.pop(device, None)

    def copy_on(self, device: int) -> Optional[DataCopy]:
        with self._lock:
            return self._copies.get(device)

    def copies(self) -> Dict[int, DataCopy]:
        with self._lock:
            return dict(self._copies)

    def create_copy(self, device: int, payload: Any = None,
                    coherency: Coherency = Coherency.INVALID,
                    version: int = 0) -> DataCopy:
        return self.attach_copy(DataCopy(self, device, payload, coherency,
                                         version))

    # -- coherency protocol ----------------------------------------------
    def newest_version(self) -> int:
        with self._lock:
            return max((c.version for c in self._copies.values()
                        if c.coherency != Coherency.INVALID), default=0)

    def newest_copy(self, prefer_device: Optional[int] = None) -> Optional[DataCopy]:
        """The authoritative valid copy (highest version, OWNED/EXCLUSIVE
        preferred, then prefer_device)."""
        with self._lock:
            best = None
            v = self.newest_version()
            for c in self._copies.values():
                if c.coherency == Coherency.INVALID or c.version != v:
                    continue
                if best is None:
                    best = c
                elif (c.coherency in (Coherency.OWNED, Coherency.EXCLUSIVE)
                      and best.coherency == Coherency.SHARED):
                    best = c
                elif prefer_device is not None and c.device == prefer_device \
                        and best.device != prefer_device:
                    if best.coherency == Coherency.SHARED or \
                       c.coherency != Coherency.SHARED:
                        best = c
            return best

    def transfer_ownership(self, device: int, access: int) -> Optional[DataCopy]:
        """Update coherency for an upcoming access on ``device``; returns the
        source copy a transfer must pull from (None if the local copy is
        already valid).  Mirrors parsec_data_transfer_ownership_to_copy
        (reference: parsec/data.h:115-126, data.c).
        """
        with self._lock:
            target = self._copies.get(device)
            if target is None:
                raise KeyError(f"no copy of {self} on device {device}")
            newest = self.newest_copy(prefer_device=device)
            source = None
            # A pull is only needed when the access actually reads the datum
            # (WRITE-only flows overwrite it entirely).
            if (access & ACCESS_READ) and (
                    target.coherency == Coherency.INVALID or
                    (newest is not None and target.version < newest.version)):
                source = newest if newest is not target else None
            if access & ACCESS_WRITE:
                for c in self._copies.values():
                    if c is not target:
                        c.coherency = Coherency.INVALID
                target.coherency = Coherency.EXCLUSIVE
            else:
                if target.coherency == Coherency.INVALID:
                    target.coherency = Coherency.SHARED
                    if newest is not None and newest.coherency == Coherency.EXCLUSIVE:
                        newest.coherency = Coherency.OWNED
                # valid copies stay as they are on read
            return source

    def complete_write(self, device: int) -> None:
        """Version bump after a write completes on ``device``.  Uses the
        monotonic clock, not max-over-valid-copies, so invalidated stale
        copies can never out-version the authoritative one."""
        with self._lock:
            c = self._copies[device]
            self._version_clock += 1
            c.version = self._version_clock

    def overwrite_on(self, space: int, payload) -> "DataCopy":
        """Land ``payload`` (an already-materialized buffer — e.g. a
        device array) as the NEW authoritative copy on ``space``: every
        other copy invalidates, the version clock bumps.  The device-
        space sibling of :meth:`overwrite_host`, keeping the write
        transition in Data rather than in every caller."""
        with self._lock:
            dc = self._copies.get(space)
            if dc is None:
                dc = self.create_copy(space, payload=payload)
            else:
                dc.payload = payload
            for c in self._copies.values():
                if c is not dc:
                    c.coherency = Coherency.INVALID
            self._version_clock += 1
            dc.version = self._version_clock
            dc.coherency = Coherency.EXCLUSIVE
            return dc

    def overwrite_host(self, arr) -> "DataCopy":
        """Land ``arr`` as the NEW authoritative host value: write in
        place when the host buffer matches (collection backing views
        stay linked), invalidate every other copy, bump the version
        clock.  The one sanctioned externally-sourced write — network
        payloads, checkpoint restore — so the coherency transition lives
        here, not in every caller."""
        import numpy as _np
        a = _np.asarray(arr)
        with self._lock:
            host = self._copies.get(0)
            if host is None:
                host = self.create_copy(0, payload=a.copy())
            elif isinstance(host.payload, _np.ndarray) and \
                    host.payload.shape == a.shape and \
                    host.payload.dtype == a.dtype:
                _np.copyto(host.payload, a)
            else:
                host.payload = a.copy()
            for c in self._copies.values():
                if c is not host:
                    c.coherency = Coherency.INVALID
            self._version_clock += 1
            host.version = self._version_clock
            host.coherency = Coherency.EXCLUSIVE
            return host

    def pull_to_host(self) -> Optional[DataCopy]:
        """Make the host copy current WITHOUT stealing ownership: the
        newest device copy stays valid (EXCLUSIVE degrades to OWNED) so
        device-resident data is readable on the host yet needs no re-stage
        on its next device use.  This is the read path of collections
        (to_array & friends); tasks use transfer_ownership instead."""
        import numpy as np
        with self._lock:
            host = self._copies.get(0)
            newest = self.newest_copy(prefer_device=0)
            if newest is None or newest is host or (
                    host is not None and
                    host.coherency != Coherency.INVALID and
                    host.version >= newest.version):
                pass   # already current: no D2H transfer
            else:
                arr = np.asarray(newest.payload)
                if host is None:
                    host = self.create_copy(0, payload=arr.copy(),
                                            coherency=Coherency.SHARED,
                                            version=newest.version)
                else:
                    dst = host.payload
                    if isinstance(dst, np.ndarray) and dst.flags.writeable:
                        np.copyto(dst, arr)
                    else:
                        # host slot holds a read-only/foreign payload (e.g.
                        # a jax array bound by a functional body): replace
                        host.payload = arr.copy()
                    host.version = newest.version
                    host.coherency = Coherency.SHARED
                if newest.coherency == Coherency.EXCLUSIVE:
                    newest.coherency = Coherency.OWNED
            # NOTE: no backing re-link here — pull_to_host runs mid-run
            # (eviction write-back) while pinned snapshot readers may
            # still hold the old backing view; re-linking happens only at
            # quiescent points (taskpool termination, to_array, device
            # flush at fini) via collection.refresh_backing.
            return host

    def start_read(self, device: int) -> None:
        with self._lock:
            self._copies[device].readers += 1

    def end_read(self, device: int) -> None:
        with self._lock:
            self._copies[device].readers -= 1


def new_data(payload: Any, key: Any = None, device: int = 0,
             collection: Any = None) -> Data:
    """Wrap an existing host payload as an OWNED datum (the common path for
    collection-backed tiles)."""
    nb = getattr(payload, "nbytes", 0)
    d = Data(key=key, collection=collection, nb_elts=nb, owner_device=device)
    d.create_copy(device, payload=payload, coherency=Coherency.OWNED, version=1)
    return d
