"""Arenas: shaped freelist allocators for temporaries.

Rebuild of the reference's arena system (reference: parsec/arena.{c,h}):
an arena defines the "shape" (size/alignment/datatype) of the temporary
buffers a taskpool needs for network staging and NEW flows; allocation goes
through a freelist so steady-state execution allocates nothing.  Here the
shape is (shape, dtype) of a numpy buffer, and ``ArenaDatatype`` pairs an
arena with a layout tag the way parsec_arena_datatype_t pairs arena+MPI
datatype (reference: parsec/parsec_internal.h:41-45).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Tuple

import numpy as np

from parsec_tpu.data.data import Coherency, Data, DataCopy


class Arena:
    #: guards DataCopy.arena_refs mutations: repo-entry holds are taken
    #: and dropped from different worker threads (release_deps vs a
    #: predecessor's retirement), and a lost update would either free a
    #: chained NEW-flow buffer early (corruption) or leak it.  One
    #: class-level lock — the critical sections are a few instructions
    _refs_lock = threading.Lock()

    def __init__(self, shape: Tuple[int, ...], dtype: Any = np.float32,
                 max_cached: int = 256):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.elt_size = int(np.prod(self.shape)) * self.dtype.itemsize
        self._lock = threading.Lock()
        self._free: List[np.ndarray] = []
        self._max = max_cached
        self.allocated = 0   # live stats (reference: arena used/released counts)
        self.released = 0

    def get_buffer(self) -> np.ndarray:
        with self._lock:
            if self._free:
                return self._free.pop()
            self.allocated += 1
        return np.empty(self.shape, self.dtype)

    def release_buffer(self, buf: np.ndarray) -> None:
        with self._lock:
            self.released += 1
            if len(self._free) < self._max:
                self._free.append(buf)

    def get_copy(self, data: Optional[Data] = None, device: int = 0) -> DataCopy:
        """Allocate a fresh arena-backed copy, optionally attached to a datum
        (reference: parsec_arena_get_copy, arena.h:136)."""
        buf = self.get_buffer()
        if data is None:
            data = Data(nb_elts=self.elt_size)
        copy = DataCopy(data, device, payload=buf,
                        coherency=Coherency.EXCLUSIVE, version=0)
        copy.arena = self
        if data.copy_on(device) is None:
            data.attach_copy(copy)
        return copy

    def release_copy(self, copy: DataCopy) -> None:
        if copy.arena is not self:
            raise ValueError("copy does not belong to this arena")
        # Swap payload->None under _refs_lock so racing releasers (repo
        # retirement vs device completer, both legitimately observing
        # refs==0) cannot both see a non-None payload and double-free the
        # buffer onto the freelist.
        with Arena._refs_lock:
            buf, copy.payload = copy.payload, None
            if buf is not None:
                copy.coherency = Coherency.INVALID
        if buf is None:
            return    # already released (idempotent: multiple lifetime
                      # managers may race to the same conclusion)
        self.release_buffer(buf)

    # -- repo-entry holds (reference: refcounted repo copies,
    # datarepo.h:50-58 — a NEW-flow buffer chained through several tasks
    # is registered in every producer's entry; only the LAST drop may
    # return it to the freelist) -----------------------------------------
    def retain_copy(self, copy: DataCopy) -> None:
        with Arena._refs_lock:
            copy.arena_refs += 1

    def drop_copy(self, copy: DataCopy) -> None:
        """Drop one hold; frees the buffer when the count reaches zero."""
        with Arena._refs_lock:
            copy.arena_refs -= 1
            free = copy.arena_refs <= 0
        if free:
            self.release_copy(copy)

    def release_unheld(self, copy: DataCopy) -> None:
        """Free only if NO entry holds the copy (supersede/remote-only
        paths, where the releasing site is not itself a hold owner)."""
        with Arena._refs_lock:
            held = copy.arena_refs > 0
        if not held:
            self.release_copy(copy)


class ArenaDatatype:
    """Arena + layout tag pair, registered per flow datatype
    (reference: parsec_arena_datatype_t)."""

    def __init__(self, arena: Arena, dtt: Any = None):
        self.arena = arena
        self.dtt = dtt if dtt is not None else (arena.shape, arena.dtype.str)
