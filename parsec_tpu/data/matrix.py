"""Tiled-matrix collections.

Rebuild of the reference's matrix data distributions
(reference: parsec/data_dist/matrix/matrix.{c,h},
two_dim_rectangle_cyclic.{c,h}, grid_2Dcyclic.c,
sym_two_dim_rectangle_cyclic.c, two_dim_tabular.c,
vector_two_dim_cyclic.c): a logical LM x LN matrix cut into MB x NB tiles,
distributed over a process grid.  ``TwoDimBlockCyclic`` is the ScaLAPACK
PxQ block-cyclic layout (with kp/kq repetition factors); the symmetric
variant stores one triangle only; ``TwoDimTabular`` takes an arbitrary
tile->rank table; ``VectorTwoDimCyclic`` distributes a 1D tile vector.

Tiles default to TPU-friendly sizes: keep MB/NB multiples of the MXU tile
(128) and bfloat16/float32 payloads so staged tiles map straight onto the
systolic array.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from parsec_tpu.data.collection import DataCollection
from parsec_tpu.data.data import Coherency, Data, new_data


class TiledMatrix(DataCollection):
    """Base tiled matrix (reference: parsec_tiled_matrix_t)."""

    def __init__(self, mb: int, nb: int, lm: int, ln: int,
                 dtype: Any = np.float32, nodes: int = 1, myrank: int = 0,
                 name: str = "A"):
        super().__init__(nodes=nodes, myrank=myrank, name=name)
        self.mb, self.nb = mb, nb           # tile rows/cols
        self.lm, self.ln = lm, ln           # full matrix rows/cols
        self.mt = -(-lm // mb)              # tiles in row dimension
        self.nt = -(-ln // nb)
        self.dtype = np.dtype(dtype)
        self._lock = threading.Lock()
        self._tiles: Dict[Tuple[int, int], Data] = {}
        self._backing: Optional[np.ndarray] = None

    # -- keys -------------------------------------------------------------
    def data_key(self, m: int, n: int = 0) -> int:
        return m * self.nt + n

    def key_to_indices(self, key: int) -> Tuple[int, int]:
        return divmod(key, self.nt)

    # -- local storage ----------------------------------------------------
    def tile_shape(self, m: int, n: int) -> Tuple[int, int]:
        """Edge tiles may be partial."""
        return (min(self.mb, self.lm - m * self.mb),
                min(self.nb, self.ln - n * self.nb))

    def tile_exists(self, m: int, n: int = 0) -> bool:
        """Whether (m, n) is a stored tile (symmetric layouts store one
        triangle only)."""
        return 0 <= m < self.mt and 0 <= n < self.nt

    def is_local(self, *indices) -> bool:
        return self.tile_exists(*indices) and \
            self.owner_of(*indices) == self.myrank

    def from_array(self, a: np.ndarray) -> "TiledMatrix":
        """Back local tiles with views into an existing LM x LN array
        (single-rank convenience; multi-rank callers hand local arrays).
        Must be called before any tile is materialized."""
        if a.shape != (self.lm, self.ln):
            raise ValueError(f"expected {(self.lm, self.ln)}, got {a.shape}")
        with self._lock:
            if self._tiles:
                raise ValueError(
                    "from_array after tiles were materialized would detach "
                    "them from the backing array; call it first")
            self._backing = a
        return self

    def to_array(self) -> np.ndarray:
        """Gather local tiles into a full array (single-rank only)."""
        if self.nodes != 1:
            raise ValueError("to_array is single-rank only")
        if self._backing is not None:
            self._sync_backing()
            return self._backing
        out = np.zeros((self.lm, self.ln), self.dtype)
        for (m, n), d in list(self._tiles.items()):
            c = d.pull_to_host()
            tm, tn = self.tile_shape(m, n)
            payload = np.asarray(c.payload)[:tm, :tn]
            out[m * self.mb:m * self.mb + tm, n * self.nb:n * self.nb + tn] = payload
        return out

    def _tile_view(self, m: int, n: int) -> np.ndarray:
        tm, tn = self.tile_shape(m, n)
        return self._backing[m * self.mb:m * self.mb + tm,
                             n * self.nb:n * self.nb + tn]

    def _sync_backing(self) -> None:
        """Pull tiles whose newest copy lives off-host, then re-link
        replaced host payloads into the backing array (to_array is a
        quiescent point by contract)."""
        for (m, n), d in list(self._tiles.items()):
            d.pull_to_host()
            self.refresh_backing(d)

    def refresh_backing(self, datum: Data) -> None:
        """Copy a replaced host payload back into its backing slice and
        re-link the view (a ``-> DATA`` writeback replaces host copies
        with private payloads — see engine._writeback — so same-wavefront
        readers keep a pinned snapshot; once the pool quiesces the
        backing array must reflect the final value again)."""
        if self._backing is None:
            return
        _name, m, n = datum.key
        with datum._lock:
            host = datum.copy_on(0)
            if host is None or host.payload is None or \
                    host.coherency == Coherency.INVALID or \
                    host.version < datum.newest_version():
                return   # stale host: a later D2H pull refreshes instead
            view = self._tile_view(m, n)
            pay = np.asarray(host.payload)
            if not np.shares_memory(view, pay):
                np.copyto(view, pay.reshape(view.shape))
                host.payload = view

    def _make_tile(self, m: int, n: int) -> Data:
        if self._backing is not None:
            payload = self._tile_view(m, n)
        else:
            payload = np.zeros(self.tile_shape(m, n), self.dtype)
        # tile_key: the datum key IS the lineage identity the recovery
        # log records (data/collection.py)
        return new_data(payload, key=self.tile_key(m, n),
                        collection=self)

    def data_of(self, m: int, n: int = 0) -> Data:
        with self._lock:
            t = self._tiles.get((m, n))
            if t is None:
                # owner_of, not rank_of: after a recovery re-mapping
                # this rank legitimately serves adopted tiles of a dead
                # rank's partition (their payloads are restored by the
                # RecoveryCoordinator before any task reads them)
                if self.owner_of(m, n) != self.myrank:
                    raise KeyError(
                        f"{self.name}({m},{n}) lives on rank "
                        f"{self.owner_of(m, n)}, not {self.myrank}")
                t = self._make_tile(m, n)
                self._tiles[(m, n)] = t
            return t

    def local_tiles(self) -> List[Tuple[int, int]]:
        return [(m, n) for m in range(self.mt) for n in range(self.nt)
                if self.tile_exists(m, n)
                and self.owner_of(m, n) == self.myrank]

    def distribute_devices(self, context_or_spaces) -> "TiledMatrix":
        """Pin local tiles block-cyclically over the process's accelerator
        memory spaces (the intra-rank analog of rank_of: owner-computes
        over the device mesh; reference: data-affinity device selection,
        device.c:79-140).  Accepts a Context or an explicit list of
        memory-space indices."""
        spaces = context_or_spaces
        if hasattr(spaces, "device_registry"):
            spaces = [d.space
                      for d in spaces.device_registry.accelerators]
        spaces = list(spaces)
        if not spaces:
            return self
        for (m, n) in [(m, n) for m in range(self.mt)
                       for n in range(self.nt) if self.tile_exists(m, n)
                       and self.rank_of(m, n) == self.myrank]:
            self.data_of(m, n).preferred_device = \
                spaces[(m * self.nt + n) % len(spaces)]
        return self


class Grid2DCyclic:
    """PxQ process grid with kp/kq repetition (reference: grid_2Dcyclic.c)."""

    def __init__(self, rank: int, P: int, Q: int, kp: int = 1, kq: int = 1,
                 ip: int = 0, jq: int = 0):
        self.rank, self.P, self.Q = rank, P, Q
        self.kp, self.kq = kp, kq
        self.ip, self.jq = ip, jq      # origin offsets
        self.rrank = rank // Q
        self.crank = rank % Q

    def rank_of(self, m: int, n: int) -> int:
        p = ((m // self.kp) + self.ip) % self.P
        q = ((n // self.kq) + self.jq) % self.Q
        return p * self.Q + q


class TwoDimBlockCyclic(TiledMatrix):
    """ScaLAPACK 2D block-cyclic distribution
    (reference: two_dim_rectangle_cyclic.{c,h})."""

    def __init__(self, mb: int, nb: int, lm: int, ln: int,
                 nodes: int = 1, myrank: int = 0, P: int = 1, Q: int = -1,
                 kp: int = 1, kq: int = 1, dtype: Any = np.float32,
                 name: str = "A"):
        super().__init__(mb, nb, lm, ln, dtype=dtype, nodes=nodes,
                         myrank=myrank, name=name)
        if Q == -1:
            Q = nodes // P
        if P * Q != nodes:
            raise ValueError(f"grid {P}x{Q} != {nodes} nodes")
        self.grid = Grid2DCyclic(myrank, P, Q, kp, kq)

    def rank_of(self, m: int, n: int = 0) -> int:
        return self.grid.rank_of(m, n)

    def vpid_of(self, m: int, n: int = 0) -> int:
        return 0


class KCyclicView(DataCollection):
    """Pseudo k-cyclic reordered VIEW of a plain block-cyclic matrix:
    shares the origin's storage, permutes the ACCESS ORDER (reference:
    parsec_matrix_block_cyclic_kview + kview_compute_m/n,
    two_dim_rectangle_cyclic.c:425-463).  This is not a copy and not the
    same order as a physically k-cyclic distribution — tile (m, n) of the
    view resolves to tile (pm(m), pn(n)) of the origin."""

    def __init__(self, origin: TwoDimBlockCyclic, kp: int, kq: int,
                 name: Optional[str] = None):
        if origin.grid.kp != 1 or origin.grid.kq != 1:
            # reference asserts krows == kcols == 1 on the origin
            raise ValueError("kview origin must be plain cyclic (kp=kq=1)")
        super().__init__(nodes=origin.nodes, myrank=origin.myrank,
                         name=name or (origin.name + "_kview"))
        self.origin = origin
        self.kp, self.kq = kp, kq
        # mirror the geometry so JDF globals (dA->super.mt) read through
        self.mb, self.nb = origin.mb, origin.nb
        self.lm, self.ln = origin.lm, origin.ln
        self.mt, self.nt = origin.mt, origin.nt
        self.dtype = origin.dtype

    def _pm(self, m: int) -> int:
        """kview_compute_m (two_dim_rectangle_cyclic.c:441-451)."""
        p, ps, mt = self.origin.grid.P, self.kp, self.mt
        while True:
            m = m - m % (p * ps) + (m % ps) * p + (m // ps) % p
            if m < mt:
                return m

    def _pn(self, n: int) -> int:
        """kview_compute_n (two_dim_rectangle_cyclic.c:453-463)."""
        q, qs, nt = self.origin.grid.Q, self.kq, self.nt
        while True:
            n = n - n % (q * qs) + (n % qs) * q + (n // qs) % q
            if n < nt:
                return n

    def data_key(self, m: int, n: int = 0):
        return self.origin.data_key(self._pm(m), self._pn(n))

    def rank_of(self, m: int, n: int = 0) -> int:
        return self.origin.rank_of(self._pm(m), self._pn(n))

    def vpid_of(self, m: int, n: int = 0) -> int:
        return self.origin.vpid_of(self._pm(m), self._pn(n))

    def data_of(self, m: int, n: int = 0) -> Data:
        return self.origin.data_of(self._pm(m), self._pn(n))

    def tile_exists(self, m: int, n: int = 0) -> bool:
        return self.origin.tile_exists(self._pm(m), self._pn(n))

    def key_to_indices(self, key):
        # keys are origin keys (shared storage); the inverse permutation
        # is not needed to address them
        return self.origin.key_to_indices(key)


def block_cyclic_kview(origin: TwoDimBlockCyclic, kp: int, kq: int,
                       name: Optional[str] = None) -> KCyclicView:
    """parsec_matrix_block_cyclic_kview equivalent."""
    return KCyclicView(origin, kp, kq, name=name)


class SymTwoDimBlockCyclic(TwoDimBlockCyclic):
    """Symmetric matrix storing one triangle only
    (reference: sym_two_dim_rectangle_cyclic.c)."""

    LOWER, UPPER = 0, 1

    def __init__(self, *args, uplo: int = LOWER, **kw):
        super().__init__(*args, **kw)
        self.uplo = uplo

    def tile_exists(self, m: int, n: int = 0) -> bool:
        if not super().tile_exists(m, n):
            return False
        return n <= m if self.uplo == self.LOWER else m <= n

    def _check(self, m: int, n: int) -> None:
        if self.uplo == self.LOWER and n > m:
            raise KeyError(f"{self.name}({m},{n}) not stored (lower)")
        if self.uplo == self.UPPER and m > n:
            raise KeyError(f"{self.name}({m},{n}) not stored (upper)")

    def rank_of(self, m: int, n: int = 0) -> int:
        self._check(m, n)
        return super().rank_of(m, n)

    def data_of(self, m: int, n: int = 0) -> Data:
        self._check(m, n)
        return super().data_of(m, n)


class BandTwoDimBlockCyclic(TwoDimBlockCyclic):
    """Band storage: only tiles within ``band_km`` of the diagonal exist
    (reference: two_dim_rectangle_cyclic_band.c /
    sym_two_dim_rectangle_cyclic_band.c — the *_band variants store the
    band of a (symmetric) matrix; out-of-band tiles are not stored and
    must not be addressed)."""

    LOWER = SymTwoDimBlockCyclic.LOWER
    UPPER = SymTwoDimBlockCyclic.UPPER

    def __init__(self, *args, band_km: int = 1, uplo: Optional[int] = None,
                 **kw):
        super().__init__(*args, **kw)
        self.band_km = band_km          # tiles kept each side of diagonal
        self.uplo = uplo                # None=full band, LOWER, or UPPER

    def tile_exists(self, m: int, n: int = 0) -> bool:
        if not super().tile_exists(m, n):
            return False
        d = m - n
        if self.uplo == self.LOWER and d < 0:   # below-diagonal only
            return False
        if self.uplo == self.UPPER and d > 0:
            return False
        return abs(d) <= self.band_km

    def _check_band(self, m: int, n: int) -> None:
        if not self.tile_exists(m, n):
            raise KeyError(f"{self.name}({m},{n}) outside the stored band")

    def rank_of(self, m: int, n: int = 0) -> int:
        self._check_band(m, n)
        return super().rank_of(m, n)

    def data_of(self, m: int, n: int = 0) -> Data:
        self._check_band(m, n)
        return super().data_of(m, n)


class TwoDimTabular(TiledMatrix):
    """Arbitrary tile->rank table (reference: two_dim_tabular.c)."""

    def __init__(self, mb: int, nb: int, lm: int, ln: int,
                 table: Sequence[int], nodes: int = 1, myrank: int = 0,
                 dtype: Any = np.float32, name: str = "T"):
        super().__init__(mb, nb, lm, ln, dtype=dtype, nodes=nodes,
                         myrank=myrank, name=name)
        if len(table) != self.mt * self.nt:
            raise ValueError("table must have one rank per tile")
        self.table = list(table)

    def rank_of(self, m: int, n: int = 0) -> int:
        return self.table[self.data_key(m, n)]


class VectorTwoDimCyclic(TiledMatrix):
    """1D cyclic vector of tiles (reference: vector_two_dim_cyclic.c).

    Payloads are 1D; from_array/to_array work on 1D arrays of length lm.
    """

    def __init__(self, mb: int, lm: int, nodes: int = 1, myrank: int = 0,
                 dtype: Any = np.float32, name: str = "V"):
        super().__init__(mb, 1, lm, 1, dtype=dtype, nodes=nodes,
                         myrank=myrank, name=name)

    def rank_of(self, m: int, n: int = 0) -> int:
        return m % self.nodes

    def tile_shape(self, m: int, n: int = 0) -> Tuple[int, ...]:
        """Vector payloads are 1D."""
        return (min(self.mb, self.lm - m * self.mb),)

    def from_array(self, a: np.ndarray) -> "VectorTwoDimCyclic":
        if a.shape != (self.lm,):
            raise ValueError(f"expected ({self.lm},), got {a.shape}")
        with self._lock:
            if self._tiles:
                raise ValueError("from_array must precede tile access")
            self._backing = a
        return self

    def to_array(self) -> np.ndarray:
        if self.nodes != 1:
            raise ValueError("to_array is single-rank only")
        if self._backing is not None:
            self._sync_backing()
            return self._backing
        out = np.zeros(self.lm, self.dtype)
        for (m, _n), d in list(self._tiles.items()):
            c = d.pull_to_host()
            tm = min(self.mb, self.lm - m * self.mb)
            out[m * self.mb:m * self.mb + tm] = np.asarray(c.payload)[:tm]
        return out

    def _make_tile(self, m: int, n: int) -> Data:
        if self._backing is not None:
            payload = self._tile_view(m, n)
        else:
            tm = min(self.mb, self.lm - m * self.mb)
            payload = np.zeros(tm, self.dtype)
        return new_data(payload, key=self.tile_key(m, n),
                        collection=self)

    def _tile_view(self, m: int, n: int) -> np.ndarray:
        tm = min(self.mb, self.lm - m * self.mb)
        return self._backing[m * self.mb:m * self.mb + tm]
