"""Local-queue work-stealing schedulers: ll, lfq, pbq, ltq, lhq, llp.

Reference modules: parsec/mca/sched/{ll,lfq,pbq,ltq,lhq,llp}/ and the
shared helpers of sched_local_queues_utils.h: per-execution-stream queues
(LIFOs, bounded hbbuffers, or heaps) with overflow to a system queue and
locality-ordered stealing.  Without hwloc depth on this platform the
hierarchy degenerates to (my queue) -> (neighbors by stream id) -> (system
queue), which preserves each policy's ordering semantics if not its cache
topology.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import List, Optional

from parsec_tpu.containers.lists import (Dequeue, HBBuffer, Lifo,
                                          OrderedList, make_dequeue)
from parsec_tpu.core.task import Task
from parsec_tpu.sched import Scheduler, register
from parsec_tpu.utils.mca import params

params.register("sched_lfq_queue_size", 16,
                "bounded local queue size before overflow to system queue")


class _PerStream(Scheduler):
    """Shared machinery: per-stream structure + steal + system queue.

    Distance-rescheduled tasks always go to the back of the system queue —
    the fairness contract (sched/__init__.py): an AGAIN task must not be
    immediately re-selected by the same stream ahead of the work it waits
    on.
    """

    def install(self, context):
        super().install(context)
        self._locals = {}
        self._system = make_dequeue()   # native-core backed when available
        # stats (reference: the display_stats hook, sched.h:299)
        self._n_local = 0
        self._n_steal = 0
        self._n_system = 0
        self._n_overflow = 0

    def _defer(self, tasks, distance) -> bool:
        if distance > 0:
            self._system.chain_back(tasks)
            return True
        return False

    def _make_local(self):
        raise NotImplementedError

    def flow_init(self, es):
        self._locals[es.th_id] = self._make_local()

    def _steal_order(self, es):
        ids = sorted(self._locals)
        me = ids.index(es.th_id) if es.th_id in ids else 0
        return [self._locals[ids[(me + i) % len(ids)]]
                for i in range(1, len(ids))]

    def display_stats(self, es) -> str:
        """reference: per-module queue/steal statistics (sched.h:299)."""
        return (f"{self.name}: local={self._n_local} "
                f"steals={self._n_steal} system={self._n_system} "
                f"overflow={self._n_overflow} "
                f"system_pending={len(self._system)}")


class LocalLifo(_PerStream):
    """ll: one LIFO per stream, steal from others
    (reference: sched_ll_module.c)."""

    def _make_local(self):
        return Lifo()

    def schedule(self, es, tasks, distance=0):
        if self._defer(tasks, distance):
            return
        q = self._locals.get(es.th_id)
        if q is None:
            self._system.chain_back(tasks)
            return
        q.push_chain(tasks)

    def select(self, es):
        q = self._locals.get(es.th_id)
        if q is not None:
            t = q.pop()
            if t is not None:
                self._n_local += 1
                return t
        for other in self._steal_order(es):
            t = other.pop()
            if t is not None:
                self._n_steal += 1
                return t
        t = self._system.pop_front()
        if t is not None:
            self._n_system += 1
        return t


class LocalFlatQueues(_PerStream):
    """lfq: bounded per-stream hbbuffer chained to the system queue,
    locality-aware steal (reference: sched_lfq_module.c + hbbuffer —
    pushes overflow UP the chain, pops walk DOWN it)."""

    def _make_local(self):
        return HBBuffer(int(params.get("sched_lfq_queue_size", 16)),
                        parent=self._system)

    def schedule(self, es, tasks, distance=0):
        if self._defer(tasks, distance):
            return
        q = self._locals.get(es.th_id)
        if q is None:
            self._system.chain_back(tasks)
            return
        before = len(self._system)
        q.chain_back(tasks)                 # overflow rides the chain
        self._n_overflow += max(0, len(self._system) - before)

    def select(self, es):
        q = self._locals.get(es.th_id)
        if q is not None:
            # LOCAL only here: the system store must come AFTER stealing
            # or a distance-deferred AGAIN task gets re-selected ahead of
            # the work it waits on (the fairness contract)
            t = q.pop_front(local_only=True)
            if t is not None:
                self._n_local += 1
                return t
        for other in self._steal_order(es):
            t = other.pop_back()            # steal the cold LOCAL end
            if t is not None:
                self._n_steal += 1
                return t
        t = self._system.pop_front()
        if t is not None:
            self._n_system += 1
        return t


class PriorityBasedQueues(_PerStream):
    """pbq: priority-ordered local queues + bounded overflow
    (reference: sched_pbq_module.c)."""

    def _make_local(self):
        return OrderedList()

    def schedule(self, es, tasks, distance=0):
        if self._defer(tasks, distance):
            return
        q = self._locals.get(es.th_id)
        if q is None:
            self._system.chain_back(tasks)
            return
        q.chain_sorted(tasks)

    def select(self, es):
        q = self._locals.get(es.th_id)
        if q is not None:
            t = q.pop_front()
            if t is not None:
                self._n_local += 1
                return t
        for other in self._steal_order(es):
            t = other.pop_back()            # steal lowest-priority end
            if t is not None:
                self._n_steal += 1
                return t
        t = self._system.pop_front()
        if t is not None:
            self._n_system += 1
        return t


class _HeapLocal:
    """Lock-protected max-heap of tasks (reference: parsec/maxheap.c)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._heap = []
        self._seq = itertools.count()

    def push(self, tasks):
        with self._lock:
            for t in tasks:
                heapq.heappush(self._heap, (-t.priority, next(self._seq), t))

    def pop(self):
        with self._lock:
            return heapq.heappop(self._heap)[2] if self._heap else None


class LocalTreeQueues(_PerStream):
    """ltq: per-stream maxheaps with stealing
    (reference: sched_ltq_module.c)."""

    def _make_local(self):
        return _HeapLocal()

    def schedule(self, es, tasks, distance=0):
        if self._defer(tasks, distance):
            return
        q = self._locals.get(es.th_id)
        if q is None:
            self._system.chain_back(tasks)
            return
        q.push(tasks)

    def select(self, es):
        q = self._locals.get(es.th_id)
        if q is not None:
            t = q.pop()
            if t is not None:
                self._n_local += 1
                return t
        for other in self._steal_order(es):
            t = other.pop()
            if t is not None:
                self._n_steal += 1
                return t
        t = self._system.pop_front()
        if t is not None:
            self._n_system += 1
        return t


params.register("sched_lhq_group_size", 2,
                "streams per intermediate hierarchy level in lhq")


class LocalHierQueues(_PerStream):
    """lhq: HIERARCHICAL local queues (reference: sched_lhq_module.c —
    hbbuffers chained per topology level).  Without hwloc the levels are
    synthesized from stream ids: per-stream HBBuffer -> per-GROUP
    HBBuffer (``sched_lhq_group_size`` streams, 4x capacity) -> system
    queue.  Overflow walks UP the chain on push; selection walks DOWN it
    on pop, then steals sibling streams of the same group, then other
    groups' buffers, then any stream."""

    def install(self, context):
        super().install(context)
        self._groups = {}   # group id -> shared mid-level HBBuffer
        self._vpmap = getattr(context, "vpmap", None)

    def _gid(self, th_id: int) -> int:
        # topology-aware when the context's vpmap carries real structure
        # (hardware split or a vpmap FILE, reference: the hwloc-level
        # hbbuffer chains of sched_lhq_module.c:30-44); otherwise the
        # synthetic fixed-size grouping
        if self._vpmap is not None and self._vpmap.nb_vps > 1:
            return self._vpmap.vp_of(th_id)
        return th_id // max(1, int(params.get("sched_lhq_group_size", 2)))

    def _group(self, th_id: int) -> HBBuffer:
        g = self._gid(th_id)
        q = self._groups.get(g)
        if q is None:
            cap = int(params.get("sched_lfq_queue_size", 16))
            q = self._groups.setdefault(
                g, HBBuffer(cap * 4, parent=self._system))
        return q

    def flow_init(self, es):
        cap = int(params.get("sched_lfq_queue_size", 16))
        self._locals[es.th_id] = HBBuffer(cap,
                                          parent=self._group(es.th_id))

    def schedule(self, es, tasks, distance=0):
        if self._defer(tasks, distance):
            return
        q = self._locals.get(es.th_id)
        if q is None:
            self._system.chain_back(tasks)
            return
        before = len(self._system)
        q.chain_back(tasks)                 # overflow climbs the chain
        self._n_overflow += max(0, len(self._system) - before)

    def select(self, es):
        q = self._locals.get(es.th_id)
        if q is not None:
            t = q.pop_front(local_only=True)
            if t is not None:
                self._n_local += 1
                return t
        grp = self._group(es.th_id)
        t = grp.pop_front(local_only=True)  # my group's shared level;
        if t is not None:                   # the system store waits its
            self._n_local += 1              # turn AFTER stealing
            return t
        me = self._gid(es.th_id)
        # steal: sibling streams in my group first (cache locality),
        # then other groups' shared buffers, then their streams
        for tid in sorted(self._locals):
            if tid != es.th_id and self._gid(tid) == me:
                t = self._locals[tid].pop_back()
                if t is not None:
                    self._n_steal += 1
                    return t
        for g in sorted(self._groups):
            if g != me:
                t = self._groups[g].pop_back()
                if t is not None:
                    self._n_steal += 1
                    return t
        for other in self._steal_order(es):
            t = other.pop_back()
            if t is not None:
                self._n_steal += 1
                return t
        t = self._system.pop_front()
        if t is not None:
            self._n_system += 1
        return t


class _HeapRingLifo:
    """LIFO of priority heaps: each schedule() call pushes its task chain
    as ONE priority-sorted ring (reference: the task rings of
    sched_llp_module.c / parsec_list_item_ring)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stack: List[List] = []
        self._seq = itertools.count()

    def push_ring(self, tasks):
        ring = []
        for t in tasks:
            heapq.heappush(ring, (-t.priority, next(self._seq), t))
        with self._lock:
            self._stack.append(ring)

    def pop_best(self):
        with self._lock:
            if not self._stack:
                return None
            ring = self._stack.pop()
            t = heapq.heappop(ring)[2]
            if ring:
                self._stack.append(ring)
            return t


class LifoLocalPrio(_PerStream):
    """llp: per-VP LIFO of priority-sorted task rings (reference:
    sched_llp_module.c) — streams of one virtual process share a LIFO
    whose entries are whole released-task rings, newest ring first,
    highest priority within the ring first."""

    def install(self, context):
        super().install(context)
        self._vps = {}      # vp id -> _HeapRingLifo

    def _make_local(self):
        return None         # structures are per-VP, not per-stream

    def _vp(self, es) -> _HeapRingLifo:
        v = self._vps.get(es.vp_id)
        if v is None:
            v = self._vps.setdefault(es.vp_id, _HeapRingLifo())
        return v

    def flow_init(self, es):
        self._locals[es.th_id] = es.vp_id
        self._vp(es)

    def schedule(self, es, tasks, distance=0):
        if self._defer(tasks, distance):
            return
        self._vp(es).push_ring(tasks)

    def select(self, es):
        t = self._vp(es).pop_best()
        if t is not None:
            self._n_local += 1
            return t
        me = es.vp_id
        for v in sorted(self._vps):
            if v != me:
                t = self._vps[v].pop_best()
                if t is not None:
                    self._n_steal += 1
                    return t
        t = self._system.pop_front()
        if t is not None:
            self._n_system += 1
        return t


register("ll", LocalLifo, priority=40)
register("lfq", LocalFlatQueues, priority=50)   # reference default
register("pbq", PriorityBasedQueues, priority=35)
register("ltq", LocalTreeQueues, priority=25)
register("lhq", LocalHierQueues, priority=15)
register("llp", LifoLocalPrio, priority=15)
