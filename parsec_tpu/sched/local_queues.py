"""Local-queue work-stealing schedulers: ll, lfq, pbq, ltq, lhq, llp.

Reference modules: parsec/mca/sched/{ll,lfq,pbq,ltq,lhq,llp}/ and the
shared helpers of sched_local_queues_utils.h: per-execution-stream queues
(LIFOs, bounded hbbuffers, or heaps) with overflow to a system queue and
locality-ordered stealing.  Without hwloc depth on this platform the
hierarchy degenerates to (my queue) -> (neighbors by stream id) -> (system
queue), which preserves each policy's ordering semantics if not its cache
topology.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import List, Optional

from parsec_tpu.containers.lists import Dequeue, Lifo, OrderedList
from parsec_tpu.core.task import Task
from parsec_tpu.sched import Scheduler, register
from parsec_tpu.utils.mca import params

params.register("sched_lfq_queue_size", 16,
                "bounded local queue size before overflow to system queue")


class _PerStream(Scheduler):
    """Shared machinery: per-stream structure + steal + system queue.

    Distance-rescheduled tasks always go to the back of the system queue —
    the fairness contract (sched/__init__.py): an AGAIN task must not be
    immediately re-selected by the same stream ahead of the work it waits
    on.
    """

    def install(self, context):
        super().install(context)
        self._locals = {}
        self._system = Dequeue()

    def _defer(self, tasks, distance) -> bool:
        if distance > 0:
            self._system.chain_back(tasks)
            return True
        return False

    def _make_local(self):
        raise NotImplementedError

    def flow_init(self, es):
        self._locals[es.th_id] = self._make_local()

    def _steal_order(self, es):
        ids = sorted(self._locals)
        me = ids.index(es.th_id) if es.th_id in ids else 0
        return [self._locals[ids[(me + i) % len(ids)]]
                for i in range(1, len(ids))]


class LocalLifo(_PerStream):
    """ll: one LIFO per stream, steal from others
    (reference: sched_ll_module.c)."""

    def _make_local(self):
        return Lifo()

    def schedule(self, es, tasks, distance=0):
        if self._defer(tasks, distance):
            return
        q = self._locals.get(es.th_id)
        if q is None:
            self._system.chain_back(tasks)
            return
        q.push_chain(tasks)

    def select(self, es):
        q = self._locals.get(es.th_id)
        if q is not None:
            t = q.pop()
            if t is not None:
                return t
        for other in self._steal_order(es):
            t = other.pop()
            if t is not None:
                return t
        return self._system.pop_front()


class LocalFlatQueues(_PerStream):
    """lfq: bounded per-stream buffer, overflow to the system queue,
    locality-aware steal (reference: sched_lfq_module.c + hbbuffer)."""

    def _make_local(self):
        return Dequeue()

    def schedule(self, es, tasks, distance=0):
        if self._defer(tasks, distance):
            return
        q = self._locals.get(es.th_id)
        cap = params.get("sched_lfq_queue_size", 16)
        if q is None:
            self._system.chain_back(tasks)
            return
        for t in tasks:
            if len(q) < cap:
                q.push_back(t)
            else:
                self._system.push_back(t)   # hbbuffer overflow to parent

    def select(self, es):
        q = self._locals.get(es.th_id)
        if q is not None:
            t = q.pop_front()
            if t is not None:
                return t
        for other in self._steal_order(es):
            t = other.pop_back()            # steal the cold end
            if t is not None:
                return t
        return self._system.pop_front()


class PriorityBasedQueues(_PerStream):
    """pbq: priority-ordered local queues + bounded overflow
    (reference: sched_pbq_module.c)."""

    def _make_local(self):
        return OrderedList()

    def schedule(self, es, tasks, distance=0):
        if self._defer(tasks, distance):
            return
        q = self._locals.get(es.th_id)
        if q is None:
            self._system.chain_back(tasks)
            return
        q.chain_sorted(tasks)

    def select(self, es):
        q = self._locals.get(es.th_id)
        if q is not None:
            t = q.pop_front()
            if t is not None:
                return t
        for other in self._steal_order(es):
            t = other.pop_back()            # steal lowest-priority end
            if t is not None:
                return t
        return self._system.pop_front()


class _HeapLocal:
    """Lock-protected max-heap of tasks (reference: parsec/maxheap.c)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._heap = []
        self._seq = itertools.count()

    def push(self, tasks):
        with self._lock:
            for t in tasks:
                heapq.heappush(self._heap, (-t.priority, next(self._seq), t))

    def pop(self):
        with self._lock:
            return heapq.heappop(self._heap)[2] if self._heap else None


class LocalTreeQueues(_PerStream):
    """ltq: per-stream maxheaps with stealing
    (reference: sched_ltq_module.c)."""

    def _make_local(self):
        return _HeapLocal()

    def schedule(self, es, tasks, distance=0):
        if self._defer(tasks, distance):
            return
        q = self._locals.get(es.th_id)
        if q is None:
            self._system.chain_back(tasks)
            return
        q.push(tasks)

    def select(self, es):
        q = self._locals.get(es.th_id)
        if q is not None:
            t = q.pop()
            if t is not None:
                return t
        for other in self._steal_order(es):
            t = other.pop()
            if t is not None:
                return t
        return self._system.pop_front()


class LocalHierQueues(LocalFlatQueues):
    """lhq: hierarchical local queues; with a flat topology behaves as lfq
    with deeper overflow (reference: sched_lhq_module.c)."""


class LifoLocalPrio(LocalTreeQueues):
    """llp: per-VP LIFO of priority heaps; degenerates to ltq on one VP
    (reference: sched_llp_module.c)."""


register("ll", LocalLifo, priority=40)
register("lfq", LocalFlatQueues, priority=50)   # reference default
register("pbq", PriorityBasedQueues, priority=35)
register("ltq", LocalTreeQueues, priority=25)
register("lhq", LocalHierQueues, priority=15)
register("llp", LifoLocalPrio, priority=15)
