"""Globally-shared-structure schedulers: gd, ip, ap, spq, rnd.

Reference modules: parsec/mca/sched/{gd,ip,ap,spq,rnd}/ — the simplest
correct policies, all built on one shared structure per virtual process.
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
from typing import List, Optional

from parsec_tpu.containers.lists import Dequeue, OrderedList, make_dequeue
from parsec_tpu.core.task import Task
from parsec_tpu.sched import Scheduler, register


class GlobalDequeue(Scheduler):
    """gd: one global FIFO dequeue — push back, pop front
    (reference: sched_gd_module.c)."""

    def install(self, context):
        super().install(context)
        self._q = make_dequeue()   # native-core backed when available

    def schedule(self, es, tasks, distance=0):
        self._q.chain_back(tasks)

    def select(self, es):
        return self._q.pop_front()


class InPlace(Scheduler):
    """ip: LIFO-ordered global list — newly released tasks run first
    (reference: sched_ip_module.c)."""

    def install(self, context):
        super().install(context)
        self._q = Dequeue()

    def schedule(self, es, tasks, distance=0):
        if distance > 0:
            self._q.chain_back(tasks)
        else:
            self._q.chain_front(tasks)

    def select(self, es):
        return self._q.pop_front()


class AbsolutePriority(Scheduler):
    """ap: single shared priority list (reference: sched_ap_module.c).
    Distance-rescheduled tasks go to the cold end so an AGAIN task cannot
    starve the work it waits on (fairness contract, sched/__init__.py)."""

    def install(self, context):
        super().install(context)
        self._q = OrderedList()

    def schedule(self, es, tasks, distance=0):
        if distance > 0:
            for t in tasks:
                self._q.push_back(t)
        else:
            self._q.chain_sorted(tasks)

    def select(self, es):
        return self._q.pop_front()


class SortedPriorityQueue(Scheduler):
    """spq: sorted by scheduling distance then priority — the documented
    example scheduler (reference: sched.h:87-99, sched_spq_module.c)."""

    def install(self, context):
        super().install(context)
        self._lock = threading.Lock()
        self._heap = []
        self._seq = itertools.count()

    def schedule(self, es, tasks, distance=0):
        with self._lock:
            for t in tasks:
                heapq.heappush(self._heap,
                               (distance, -t.priority, next(self._seq), t))

    def select(self, es):
        with self._lock:
            if not self._heap:
                return None
            return heapq.heappop(self._heap)[3]


class RandomSched(Scheduler):
    """rnd: random selection from a global list
    (reference: sched_rnd_module.c)."""

    def install(self, context):
        super().install(context)
        self._lock = threading.Lock()
        self._items: List[Task] = []

    def schedule(self, es, tasks, distance=0):
        with self._lock:
            self._items.extend(tasks)

    def select(self, es):
        with self._lock:
            if not self._items:
                return None
            i = random.randrange(len(self._items))
            self._items[i], self._items[-1] = self._items[-1], self._items[i]
            return self._items.pop()


register("gd", GlobalDequeue, priority=10)
register("ip", InPlace, priority=5)
register("ap", AbsolutePriority, priority=20)
register("spq", SortedPriorityQueue, priority=30)
register("rnd", RandomSched, priority=1)
