"""Native ready-queue scheduler (MCA component ``native``).

The scheduler inner loop in C (parsec_tpu/native/schedext.c): one
METH_FASTCALL crossing per scheduling event carries the whole ready
ring through READY-transition + ``Task.ready_at`` stamping +
priority-ordered insert, and one crossing pops the next task — no
Python-level lock (the GIL is the queue lock; the Python schedulers
pay a ``threading.Lock`` round-trip per queue op ON TOP of the GIL,
which is exactly the contention the 4-worker tasks probe measured).

Selection: ``--mca sched native`` explicitly, or the default when
``sched_native`` (env ``PARSEC_MCA_SCHED_NATIVE``, default 1) is on
and the extension builds — sched/__init__.create.  The A/B knob:
``PARSEC_MCA_SCHED_NATIVE=0`` restores the Python component ladder
(lfq by default) for paired measurement; a missing toolchain degrades
the same way, counted in ``fallbacks()`` for the metrics plane.

Ordering contract: priority-ordered, FIFO among equal priorities (the
``ap`` discipline); distance-rescheduled tasks go behind EVERYTHING
(the sched/__init__.py fairness contract — an AGAIN task must not be
re-selected ahead of the work it waits on).
"""

from __future__ import annotations

from typing import List, Optional

from parsec_tpu.core.task import Task, TaskStatus
from parsec_tpu.sched import Scheduler, register
from parsec_tpu.utils.mca import params

params.register("sched_native", 1,
                "use the native (C) ready-queue scheduler when no "
                "explicit sched component is requested and the "
                "extension builds (0 = the Python component ladder; "
                "the tasks-probe A/B knob)")

#: times the native path was requested but the extension was not
#: usable (scrape-time metrics: parsec_sched_native_fallbacks_total)
_fallbacks = 0


def fallbacks() -> int:
    return _fallbacks


def note_fallback() -> None:
    global _fallbacks
    _fallbacks += 1


class NativeSched(Scheduler):
    """One global native priority queue shared by every stream."""

    #: core/scheduling.schedule hands the raw ready ring to
    #: ``schedule()`` untouched — status/ready_at land C-side
    NATIVE_BATCH = True

    def install(self, context) -> None:
        super().install(context)
        from parsec_tpu.native import load_schedext
        se = load_schedext()
        if se is None:
            raise RuntimeError("sched native: schedext did not build")
        self._q = se.ReadyQueue(TaskStatus.READY)

    # lint: hot-path (ReadyQueue callback: one call per scheduling event)
    def schedule(self, es, tasks: List[Task], distance: int = 0) -> None:
        # one crossing: READY + ready_at (when a telemetry consumer
        # wants it) + priority-heap insert for the whole ring;
        # distance > 0 pins the ring behind everything (fairness)
        self._q.push_batch(tasks, self.context._ready_stamp, distance > 0)

    # lint: hot-path (ReadyQueue callback: one call per selection)
    def select(self, es) -> Optional[Task]:
        return self._q.pop()

    def display_stats(self, es) -> str:
        pushes, pops, max_len, pending = self._q.stats()
        return (f"native: pushes={pushes} pops={pops} "
                f"max_depth={max_len} pending={pending}")

    def stats(self) -> dict:
        """Scrape-time counters (prof/metrics.py sched family)."""
        pushes, pops, max_len, pending = self._q.stats()
        return {"pushes": pushes, "pops": pops, "max_depth": max_len,
                "pending": pending}


register("native", NativeSched, priority=0)   # explicit/knob-gated only
