"""Pluggable schedulers (MCA framework ``sched``).

Rebuild of the reference's scheduler component framework
(reference: parsec/mca/sched/sched.h:325-340 interface; module inventory
SURVEY.md §2.4).  A scheduler provides install / per-stream flow_init /
schedule(es, tasks, distance) / select(es) / display_stats / remove.
The ``distance`` argument is the fairness contract of sched.h:58-99: a
task rescheduled with growing distance must not be immediately re-selected
by the same stream, or AGAIN-returning tasks livelock.

Selection: ``--mca sched <name>`` (reference: parsec_set_scheduler).
"""

from __future__ import annotations

from typing import List, Optional

from parsec_tpu.utils.mca import components
from parsec_tpu.core.task import Task


class Scheduler:
    name = "base"

    #: native-batch contract: when True, core/scheduling.schedule hands
    #: the raw ready ring to ``schedule()`` without the Python
    #: status/ready_at loop — the scheduler performs the READY
    #: transition (and stamping) itself, in one native crossing
    #: (sched/native.py)
    NATIVE_BATCH = False

    def install(self, context) -> None:
        self.context = context

    def flow_init(self, es) -> None:
        pass

    def schedule(self, es, tasks: List[Task], distance: int = 0) -> None:
        raise NotImplementedError

    def select(self, es) -> Optional[Task]:
        raise NotImplementedError

    def display_stats(self, es) -> str:
        return ""

    def remove(self, context) -> None:
        pass


def register(name: str, cls, priority: int = 0) -> None:
    components.add("sched", name, cls, priority=priority)


def create(name: Optional[str] = None) -> Scheduler:
    from parsec_tpu.utils.mca import params
    requested = name or (params.get("sched", "") or None)
    if requested is None and int(params.get("sched_native", 1)):
        # no explicit component named and the native hot path is on:
        # prefer the C ready queue, falling back to the Python ladder
        # when the extension does not build (counted for the metrics
        # plane — a silent no-op native path is itself a regression)
        from parsec_tpu.native import load_schedext
        if load_schedext() is not None:
            requested = "native"
        else:
            from parsec_tpu.sched import native as _native_mod
            _native_mod.note_fallback()
    selected, cls = components.select("sched", requested)
    inst = cls()
    inst.name = selected
    return inst


# Import modules so they self-register.
from parsec_tpu.sched import simple as _simple          # noqa: E402,F401
from parsec_tpu.sched import local_queues as _lq        # noqa: E402,F401
from parsec_tpu.sched import native as _native          # noqa: E402,F401
