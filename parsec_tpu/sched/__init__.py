"""Pluggable schedulers (MCA framework ``sched``).

Rebuild of the reference's scheduler component framework
(reference: parsec/mca/sched/sched.h:325-340 interface; module inventory
SURVEY.md §2.4).  A scheduler provides install / per-stream flow_init /
schedule(es, tasks, distance) / select(es) / display_stats / remove.
The ``distance`` argument is the fairness contract of sched.h:58-99: a
task rescheduled with growing distance must not be immediately re-selected
by the same stream, or AGAIN-returning tasks livelock.

Selection: ``--mca sched <name>`` (reference: parsec_set_scheduler).
"""

from __future__ import annotations

from typing import List, Optional

from parsec_tpu.utils.mca import components
from parsec_tpu.core.task import Task


class Scheduler:
    name = "base"

    def install(self, context) -> None:
        self.context = context

    def flow_init(self, es) -> None:
        pass

    def schedule(self, es, tasks: List[Task], distance: int = 0) -> None:
        raise NotImplementedError

    def select(self, es) -> Optional[Task]:
        raise NotImplementedError

    def display_stats(self, es) -> str:
        return ""

    def remove(self, context) -> None:
        pass


def register(name: str, cls, priority: int = 0) -> None:
    components.add("sched", name, cls, priority=priority)


def create(name: Optional[str] = None) -> Scheduler:
    selected, cls = components.select("sched", name)
    inst = cls()
    inst.name = selected
    return inst


# Import modules so they self-register.
from parsec_tpu.sched import simple as _simple          # noqa: E402,F401
from parsec_tpu.sched import local_queues as _lq        # noqa: E402,F401
