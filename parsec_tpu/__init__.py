"""parsec_tpu — a TPU-native task-DAG runtime.

A ground-up rebuild of the capabilities of PaRSEC (the Parallel Runtime
Scheduler and Execution Controller, reference: uiuc-hpc/parsec-1) designed
for TPU hardware: applications are expressed as DAGs of micro-tasks with
data-labeled dependency edges (parameterized task graphs or dynamic task
discovery), tile kernels execute as XLA/Pallas programs on the MXU, tiles
are staged into TPU HBM by the device layer, and dependency edges between
ranks lower onto ICI/DCN collective schedules over a `jax.sharding.Mesh`
instead of funnelled MPI.

Layer map (mirrors reference SURVEY.md §1):
  0. ``utils``/``containers`` — config registry, logging, concurrent containers
  1. ``data``                 — Data/DataCopy coherency, arenas, repos, collections
  2. ``core`` + ``sched``     — taskpools, dep-resolution engine, pluggable schedulers
  3. ``comm``                 — comm-engine vtable, remote-dep protocol, bcast trees
  4. ``device``               — device registry, TPU offload module
  5. ``dsl``                  — PTG (parameterized task graph) and DTD front-ends
  6. ``data`` collections     — tiled matrices, block-cyclic and friends
  7. ``profiling``            — binary tracing, PINS instrumentation, DOT grapher
  8. ``apps``                 — tiled Cholesky/QR/GEMM/stencil drivers
"""

__version__ = "0.1.0"

from parsec_tpu.utils import mca  # noqa: F401
from parsec_tpu.core.context import Context  # noqa: F401
from parsec_tpu.core.taskpool import (Compound, ParameterizedTaskpool,  # noqa: F401
                                      Taskpool, compose)
from parsec_tpu.core.task import (CTL, NULL, READ, RW, WRITE, Dep, Flow,  # noqa: F401
                                  FromDesc, FromTask, HookReturn, New, Task,
                                  TaskClass, ToDesc, ToTask)
