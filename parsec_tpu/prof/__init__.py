"""Observability: binary tracing, PINS instrumentation, DOT graphs, gauges.

reference: SURVEY.md §2.11/§5.1 — parsec/profiling.c binary trace +
dictionary, mca/pins/ callback framework, parsec_prof_grapher.c DOT
output, papi_sde.c live gauges, tools/profiling readers.
"""

from parsec_tpu.prof.profiling import (Profile, profiling_init,  # noqa: F401
                                       profiling_fini)
from parsec_tpu.prof.pins import TaskProfilerPins, install_task_profiler  # noqa: F401
from parsec_tpu.prof.grapher import DotGrapher  # noqa: F401
from parsec_tpu.prof.gauges import Gauges, install_gauges  # noqa: F401
from parsec_tpu.prof.reader import read_trace  # noqa: F401
from parsec_tpu.prof.causal import (CausalTracer,  # noqa: F401
                                    install_causal_tracer)
