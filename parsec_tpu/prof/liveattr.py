"""Live attribution plane: online critical-path attribution + stragglers.

The online half of the causal pipeline (reference: the PINS/PAPI-SDE
instrumentation operators read while a DAG runs; our offline half is
prof/critpath.py over merged ``.ptt`` dumps).  This module answers
"why is job 7 slow RIGHT NOW" without stopping anything:

* **per-(job, task-class) streaming latency profiles** — exact done
  counts plus sampled observations (count, EWMA, fixed log2 buckets,
  ring-reservoir p50/p95/p99) for the ready->complete sojourn, and —
  with the opt-in ``metrics_queue_wait`` select hook — separate
  queue-wait and execution profiles.  Everything rides the PR 7
  metrics hooks (``RuntimeMetrics._select``/``_complete``): NO new
  hot-path PINS crossings;
* **straggler detection** — a task whose sojourn (or queue wait)
  exceeds ``liveattr_straggler_mult`` x its class p99 (min-count
  guarded) emits a structured anomaly event, counts in
  ``parsec_stragglers_total{job,class,kind}``, and — rate-limited —
  fires the PR 7 flight recorder so the incident bundle captures the
  straggler's causal neighborhood;
* **online makespan decomposition** — each job's elapsed time
  telescopes into exec / queue / comm / idle buckets: exec and queue
  from the class profiles (sampled mean x exact done count), comm from
  the per-peer comm-delay estimates folded at SCRAPE time out of
  ``RemoteDepEngine.stats()`` (clock-probe rtt/2 + drain-delay EWMA —
  no comm-layer hooks), idle as the telescoped remainder.  On a
  serial-chain workload (the traced rtt leg) the split converges to
  the offline ``critpath.attribute()`` answer; on wide DAGs the three
  measured buckets are proportionally clamped to the elapsed window
  (documented approximation — the buckets always sum to elapsed);
* **ETA** — remaining-task counts x live class profiles through the
  calibrated dagsim list-scheduling model (parallel/dagsim.py): the
  completion quote a predictive admission controller will reuse.

Cross-rank: each rank's engine serializes a ``section()`` dict that
rides the existing TAG_METRICS pull as one extra sample record (zero
new wire tags); :func:`merge_sections` folds them (exact counts and
buckets sum; quantiles re-derived from merged buckets) and
:func:`cluster_status` builds the ``{"op": "status"}`` / ``GET
/status`` document the JobServer serves (service/server.py) and
tools/live_view.py renders.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from parsec_tpu.prof.metrics import (BUCKET_BOUNDS, _NBUCKETS,
                                     bucket_index, counter_sample)
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose

params.register("liveattr_enable", 1,
                "arm the online attribution engine on the metrics "
                "registry: per-(job, task-class) latency profiles, "
                "straggler detection, the live exec/queue/comm/idle "
                "split and the dagsim ETA behind the job server's "
                "status surface (0 disables; requires metrics_enabled)")
params.register("liveattr_ring", 128,
                "per-class-profile quantile reservoir: the most recent "
                "N sampled observations kept for the p50/p95/p99 "
                "estimates the straggler threshold arms from")
params.register("liveattr_ewma_alpha", 0.2,
                "smoothing factor of the per-class latency EWMA the "
                "status surface and the ETA's duration model read")
params.register("liveattr_max_series", 64,
                "bound on tracked (job, task-class) profile rows: past "
                "it the oldest row is dropped (a resident service must "
                "not grow O(jobs x classes))")
params.register("liveattr_straggler_mult", 8.0,
                "straggler threshold: a task whose sojourn or queue "
                "wait exceeds this multiple of its class p99 emits an "
                "anomaly event and counts in parsec_stragglers_total")
params.register("liveattr_straggler_min", 64,
                "minimum sampled observations before a class arms its "
                "straggler threshold (an unwarmed p99 over 3 samples "
                "would flag ordinary variance)")
params.register("liveattr_straggler_floor_ms", 50.0,
                "absolute straggler floor in milliseconds: the armed "
                "threshold is max(mult x class p99, this floor) — for "
                "microsecond-scale task classes a pure multiple of a "
                "tight p99 would flag every GC pause and scheduler "
                "deschedule on a loaded host")
params.register("liveattr_straggler_incident_s", 60.0,
                "rate limit on straggler-triggered flight-recorder "
                "incident dumps, seconds (the recorder's own "
                "flightrec_min_interval_s applies on top; 0 disables "
                "the trigger entirely)")
params.register("liveattr_anomaly_log", 64,
                "bounded ring of recent structured anomaly events kept "
                "for the status surface")
params.register("liveattr_sim_tasks", 512,
                "node budget of the synthetic dagsim ETA model: a "
                "job's remaining tasks beyond it are collapsed into "
                "equal-work nodes per class (total work preserved)")
params.register("liveattr_enum_max", 100000,
                "cap on enumerating a pool's per-class task totals for "
                "the progress/ETA surface; larger spaces fall back to "
                "the pool's aggregate remaining count")


# ---------------------------------------------------------------------------
# streaming per-class profiles
# ---------------------------------------------------------------------------

class _Profile:
    """One streaming latency profile: exact-ish sampled count/sum/EWMA,
    positional log2 buckets (mergeable across ranks), and a ring
    reservoir for precise local quantiles.  NOT self-locking: the
    owning record's lock covers every mutation."""

    __slots__ = ("n", "sum", "ewma", "buckets", "_ring", "_rn")

    def __init__(self, ring: int):
        self.n = 0
        self.sum = 0.0
        self.ewma = 0.0
        self.buckets = [0] * (_NBUCKETS + 1)
        self._ring: List[float] = [0.0] * max(8, ring)
        self._rn = 0

    def observe(self, x: float, alpha: float) -> None:
        self.buckets[bucket_index(x)] += 1
        self.sum += x
        self.ewma = x if self.n == 0 else \
            (1.0 - alpha) * self.ewma + alpha * x
        self.n += 1
        self._ring[self._rn % len(self._ring)] = x
        self._rn += 1

    def quantile(self, q: float) -> float:
        n = min(self._rn, len(self._ring))
        if not n:
            return 0.0
        snap = sorted(self._ring[:n])
        return snap[min(n - 1, int(q * n))]

    @property
    def mean(self) -> float:
        return self.sum / self.n if self.n else 0.0

    def to_wire(self) -> dict:
        return {"n": self.n, "sum": round(self.sum, 9),
                "ewma": round(self.ewma, 9),
                "q": [round(self.quantile(p), 9)
                      for p in (0.5, 0.95, 0.99)],
                "b": list(self.buckets)}


def bucket_quantile(buckets: List[int], q: float) -> float:
    """Quantile estimate from merged positional log2 buckets (upper
    bound of the bucket where the cumulative count crosses q — factor-2
    resolution, which is what cross-rank merged rows can offer)."""
    total = sum(buckets)
    if not total:
        return 0.0
    goal = q * total
    cum = 0
    for i, b in enumerate(buckets):
        cum += b
        if cum >= goal and b:
            return BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS) \
                else BUCKET_BOUNDS[-1] * 2.0
    return BUCKET_BOUNDS[-1] * 2.0


class _Rec:
    """Per-(job, task-class) row: exact counters + profiles + the armed
    straggler thresholds.  ``la`` back-references the owning engine so
    the per-TaskClass cache (``tc._la_rec``) can detect staleness after
    a reset/reinstall with one identity compare.

    ``done`` counts SAMPLED completions only (the metrics stride):
    the per-task hot path pays liveattr nothing — the section scales
    by the stride (exact at stride 1 and in split mode, where every
    completion reaches :meth:`LiveAttr.task_done`), and
    :func:`build_status` snaps a completed pool's counts to its
    enumerated class totals."""

    __slots__ = ("la", "job", "cls", "lock", "done", "sel",
                 "lat", "queue", "exq", "thr_lat", "thr_exec",
                 "thr_queue", "strag", "t0", "t1")

    def __init__(self, la: "LiveAttr", job, cls: str, ring: int):
        self.la = la
        self.job = job
        self.cls = cls
        self.lock = threading.Lock()
        self.done = 0                 # sampled completions (guarded-by:
        self.sel = 0                  # lock); exact selections (split)
        self.lat = _Profile(ring)     # sampled ready->complete sojourn
        self.queue = _Profile(ring)   # sampled ready->select (split mode)
        self.exq = _Profile(ring)     # sampled body interval (split)
        self.thr_lat = 0.0            # armed straggler threshold (sojourn)
        self.thr_exec = 0.0           # armed threshold (body interval)
        self.thr_queue = 0.0          # armed straggler threshold (queue)
        self.t0 = 0.0                 # first/last completion stamps
        self.t1 = 0.0                 # (perf_counter; window of activity)

    def invalidate(self) -> None:
        """Break the per-TaskClass cache binding (``rec.la is self``):
        called on eviction and reset so a class still running cannot
        keep counting into an orphaned row — its next task re-resolves
        through ``_rec_for`` and registers a live one."""
        self.la = None


class LiveAttr:
    """One per RuntimeMetrics (prof/metrics.py owns install/uninstall
    and calls :meth:`task_selected` / :meth:`task_done` from its
    existing PINS handlers — the engine itself registers nothing)."""

    def __init__(self, metrics):
        self._metrics = metrics
        self._lock = threading.Lock()
        #: (job_id-or-None, class name) -> _Rec (guarded-by: _lock)
        self._recs: Dict[Tuple, _Rec] = {}
        self._ring = max(8, int(params.get("liveattr_ring", 128)))
        self._alpha = float(params.get("liveattr_ewma_alpha", 0.2))
        self._max = int(params.get("liveattr_max_series", 64))
        self._mult = float(params.get("liveattr_straggler_mult", 8.0))
        self._min_n = int(params.get("liveattr_straggler_min", 64))
        self._floor = float(params.get("liveattr_straggler_floor_ms",
                                       50.0)) * 1e-3
        self._inc_s = float(params.get("liveattr_straggler_incident_s",
                                       60.0))
        self._anomalies: deque = deque(
            maxlen=max(4, int(params.get("liveattr_anomaly_log", 64))))
        #: per-(job, class, kind) straggler counts (guarded-by: _lock)
        self._strag_counts: Dict[Tuple, int] = {}
        self._last_incident = 0.0
        #: comm counter baseline captured at reset() so the comm bucket
        #: describes the current window, not process lifetime
        self._acts_base = 0.0

    # -- hot path (called from RuntimeMetrics PINS handlers) -------------
    def _rec_for(self, task) -> _Rec:
        """Slow half of the per-TaskClass record cache: runs once per
        (class, install) and on cache staleness."""
        tc = task.task_class
        key = (getattr(task.taskpool, "job_id", None), tc.name)
        with self._lock:
            rec = self._recs.get(key)
            if rec is None:
                rec = self._recs[key] = _Rec(self, key[0], key[1],
                                             self._ring)
                while len(self._recs) > self._max:
                    # the evicted row must not keep receiving updates
                    # through a TaskClass cache that still points at it
                    self._recs.pop(next(iter(self._recs))).invalidate()
        tc._la_rec = rec     # per-class cache; staleness via rec.la
        return rec

    def task_done(self, rec: _Rec, es, task, sampled: bool,
                  check: bool = True,
                  _perf=time.perf_counter) -> None:
        """Completion accounting.  Single-hook mode reaches here only
        for SAMPLED tasks (the metrics stride), so the engine adds
        NOTHING to the common per-task path — counts, profiles and the
        straggler check all ride the stride, exactly like the metrics
        histograms (detection probability for an isolated straggler is
        1/stride there; stride 1 or the split hooks buy full
        coverage).  Split mode calls per task (the knob opted into
        that cost).  ``check=False`` in split mode: the exec-side
        straggler check already ran at exec_end
        (:meth:`observe_exec`)."""
        hit = 0.0
        with rec.lock:
            rec.done += 1
            thr = rec.thr_lat
            now = _perf()
            rec.t1 = now
            if not rec.t0:
                rec.t0 = now
            # sojourn needs ready_at, which a co-installed causal
            # tracer legitimately consumes at select
            dt = None
            t0 = task.ready_at
            if t0 is not None and t0 <= now:
                dt = now - t0
            if sampled and dt is not None:
                rec.lat.observe(dt, self._alpha)
                if not rec.lat.n % 16:
                    self._refresh_thr(rec)
            if check and thr > 0.0 and dt is not None and dt > thr:
                hit = dt
        if hit:
            self._anomaly(rec, task, "exec", hit, rec.thr_lat)

    def observe_exec(self, task, dt: float, sampled: bool) -> None:
        """Split-mode body interval (exec_begin->exec_end, the task
        profiler's own definition): the exec profile and the exec-side
        straggler check."""
        rec = self.rec_of(task)
        hit = 0.0
        with rec.lock:
            if sampled:
                rec.exq.observe(dt, self._alpha)
                if not rec.exq.n % 16:
                    self._refresh_thr(rec)
            thr = rec.thr_exec
            if thr > 0.0 and dt > thr:
                hit = dt
        if hit:
            self._anomaly(rec, task, "exec", hit, rec.thr_exec)

    def rec_of(self, task) -> _Rec:
        """Cached per-TaskClass record (fast path + slow fallback)."""
        rec = getattr(task.task_class, "_la_rec", None)
        if rec is not None and rec.la is self:
            return rec
        return self._rec_for(task)

    def task_selected(self, task, qwait: Optional[float],
                      _perf=time.perf_counter) -> None:
        """Split-mode (metrics_queue_wait=1) selection accounting:
        exact per-class in-flight bookkeeping plus the queue-wait
        profile/straggler side."""
        rec = self.rec_of(task)
        hit = 0.0
        with rec.lock:
            rec.sel += 1
            if qwait is not None:
                rec.queue.observe(qwait, self._alpha)
                if not rec.queue.n % 16:
                    self._refresh_thr(rec)
            thr = rec.thr_queue
            if thr > 0.0:
                q = qwait
                if q is None:
                    t0 = task.ready_at
                    if t0 is not None:
                        q = _perf() - t0
                if q is not None and q > thr:
                    hit = q
        if hit:
            self._anomaly(rec, task, "queue", hit, rec.thr_queue)

    def _refresh_thr(self, rec: _Rec) -> None:
        """Recompute the armed thresholds from the ring p99 (rec.lock
        held).  Amortized: called one sampled observation in 16 — the
        sort is over the bounded ring, off every other task's path."""
        # three thresholds, each armed from ITS OWN distribution: a
        # body duration compared against a sojourn p99 would mask exec
        # stragglers of queue-dominated classes (and vice versa)
        if rec.lat.n >= self._min_n:
            rec.thr_lat = max(self._mult * rec.lat.quantile(0.99),
                              self._floor)
        if rec.exq.n >= self._min_n:
            rec.thr_exec = max(self._mult * rec.exq.quantile(0.99),
                               self._floor)
        if rec.queue.n >= self._min_n:
            rec.thr_queue = max(self._mult * rec.queue.quantile(0.99),
                                self._floor)

    # -- anomalies --------------------------------------------------------
    def _anomaly(self, rec: _Rec, task, kind: str, dt: float,
                 thr: float) -> None:
        """Structured straggler event: log it, count it, and —
        rate-limited — fire the flight recorder so the incident bundle
        captures the straggler's causal neighborhood."""
        ev = {"ts": time.time(), "job": rec.job, "cls": rec.cls,
              "kind": kind, "latency_s": round(dt, 6),
              "threshold_s": round(thr, 6), "mult": self._mult,
              "task": repr(task)[:120]}
        with self._lock:
            self._anomalies.append(ev)
            k = (rec.job, rec.cls, kind)
            self._strag_counts[k] = self._strag_counts.get(k, 0) + 1
        debug_verbose(2, "liveattr: straggler %s %s %.3fms > %.3fms",
                      rec.cls, kind, dt * 1e3, thr * 1e3)
        ctx = getattr(self._metrics, "context", None)
        if ctx is None or self._inc_s <= 0:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._last_incident < self._inc_s:
                return
            self._last_incident = now
        try:
            ctx.telemetry_incident(
                f"straggler: {rec.cls} job={rec.job} {kind} "
                f"{dt * 1e3:.1f}ms > {self._mult:g}x p99 "
                f"({thr * 1e3:.1f}ms)")
        except Exception:   # telemetry must never fail a worker
            pass

    def anomalies(self) -> List[dict]:
        with self._lock:
            return list(self._anomalies)

    # -- scrape-side ------------------------------------------------------
    def samples(self) -> List[dict]:
        """Prometheus-side additions (ride RuntimeMetrics.samples)."""
        with self._lock:
            counts = dict(self._strag_counts)
        out = []
        for (job, cls, kind), n in counts.items():
            out.append(counter_sample(
                "parsec_stragglers_total", n,
                {"job": "-" if job is None else str(job),
                 "class": cls, "kind": kind}))
        return out

    def _comm_estimate(self) -> dict:
        """Scrape-time comm-delay fold from the transport's existing
        counters: activations sent this window x the per-peer delay
        estimate (clock-probe rtt/2 + queue->wire drain EWMA,
        RemoteDepEngine.stats()).  No comm-layer hooks — the PAPI-SDE
        pattern again: read counters that already exist."""
        ctx = getattr(self._metrics, "context", None)
        comm = getattr(ctx, "comm", None) if ctx is not None else None
        if comm is None:
            return {"acts": 0.0, "delay_s": 0.0, "per_peer": {}}
        try:
            st = comm.stats()
        except Exception:
            return {"acts": 0.0, "delay_s": 0.0, "per_peer": {}}
        acts = float(st.get("act_eager", 0) + st.get("act_rdv", 0)
                     + st.get("act_inline", 0)) - self._acts_base
        per_peer = {str(r): round(v, 9) for r, v in
                    (st.get("peer_comm_delay_s") or {}).items()}
        vals = [v for v in per_peer.values() if v > 0]
        delay = sum(vals) / len(vals) if vals else 0.0
        return {"acts": max(0.0, acts), "delay_s": round(delay, 9),
                "per_peer": per_peer}

    def section(self) -> dict:
        """The per-rank wire form riding the TAG_METRICS pull."""
        ctx = getattr(self._metrics, "context", None)
        # done counts are SAMPLED in single-hook mode: scale by the
        # stride (exact at stride 1 / split mode; build_status snaps
        # completed pools to their enumerated totals)
        m = self._metrics
        scale = 1 if getattr(m, "_split_queue", False) \
            else max(1, getattr(m, "_sample", 1))
        with self._lock:
            recs = list(self._recs.values())
        rows = []
        for rec in recs:
            with rec.lock:
                rows.append({
                    "job": rec.job, "cls": rec.cls,
                    "done": rec.done * scale,
                    "sel": rec.sel, "t0": rec.t0, "t1": rec.t1,
                    "lat": rec.lat.to_wire(),
                    "queue": rec.queue.to_wire() if rec.queue.n
                    else None,
                    "exec": rec.exq.to_wire() if rec.exq.n else None,
                })
        with self._lock:
            strag = [list(k) + [n]
                     for k, n in self._strag_counts.items()]
            anomalies = list(self._anomalies)[-16:]
        return {"v": 1,
                "rank": ctx.rank if ctx is not None else 0,
                "recs": rows,
                "strag": strag,
                "anomalies": anomalies,
                "comm": self._comm_estimate()}

    def reset(self) -> None:
        """Start a fresh attribution window (benches call this after
        warmup so the split describes the measured run)."""
        with self._lock:
            for rec in self._recs.values():
                rec.invalidate()   # cached on still-live TaskClasses
            self._recs.clear()
            self._strag_counts.clear()
            self._anomalies.clear()
            self._acts_base = 0.0
        # re-baseline the comm counters OUTSIDE the lock (stats() takes
        # transport locks of its own)
        est = self._comm_estimate()
        with self._lock:
            self._acts_base += est["acts"]


# ---------------------------------------------------------------------------
# cross-rank merge + the status document
# ---------------------------------------------------------------------------

def _merge_profile(dst: Optional[dict], src: Optional[dict]) -> \
        Optional[dict]:
    if src is None:
        return dst
    if dst is None:
        return {**src, "b": list(src["b"]), "_single": True}
    n0, n1 = dst["n"], src["n"]
    dst["n"] = n0 + n1
    dst["sum"] += src["sum"]
    dst["ewma"] = ((dst["ewma"] * n0 + src["ewma"] * n1)
                   / max(1, n0 + n1))
    for i, b in enumerate(src["b"]):
        dst["b"][i] += b
    dst["_single"] = False
    return dst


def _finish_profile(p: Optional[dict]) -> Optional[dict]:
    """Non-destructive: build_status finishes the SAME merged row dict
    once per job entry and once in the aggregate section."""
    if p is None:
        return None
    n = p["n"]
    out = {"n": n, "mean_s": round(p["sum"] / n, 9) if n else 0.0,
           "ewma_s": round(p["ewma"], 9)}
    if p.get("_single", False):
        q = p.get("q") or [0.0, 0.0, 0.0]
    else:
        q = [bucket_quantile(p["b"], x) for x in (0.5, 0.95, 0.99)]
    out["p50_s"], out["p95_s"], out["p99_s"] = \
        [round(v, 9) for v in q]
    return out


def merge_sections(sections: Dict[int, dict]) -> dict:
    """Fold per-rank section dicts into one cluster view: counts and
    buckets sum, quantiles re-derive from the merged buckets, the
    activity window is the widest per-rank window (per-rank clocks are
    unaligned perf_counter timelines, so windows merge by width, never
    by endpoint)."""
    recs: Dict[Tuple, dict] = {}
    strag: Dict[Tuple, int] = {}
    anomalies: List[dict] = []
    acts_total = 0.0
    delay_max = 0.0
    window = 0.0
    per_peer: Dict[str, float] = {}
    for rank in sorted(sections):
        sec = sections[rank] or {}
        for row in sec.get("recs", ()):
            key = (row.get("job"), row.get("cls"))
            cur = recs.get(key)
            if cur is None:
                cur = recs[key] = {
                    "job": key[0], "cls": key[1], "done": 0, "sel": 0,
                    "lat": None, "queue": None, "exec": None,
                    "window_s": 0.0}
            cur["done"] += int(row.get("done", 0))
            cur["sel"] += int(row.get("sel", 0))
            for k in ("lat", "queue", "exec"):
                cur[k] = _merge_profile(cur[k], row.get(k))
            t0, t1 = row.get("t0", 0.0), row.get("t1", 0.0)
            if t1 > t0 > 0.0:
                cur["window_s"] = max(cur["window_s"], t1 - t0)
                window = max(window, t1 - t0)
        for ent in sec.get("strag", ()):
            k = tuple(ent[:3])
            strag[k] = strag.get(k, 0) + int(ent[3])
        for ev in sec.get("anomalies", ()):
            anomalies.append({**ev, "rank": sec.get("rank", rank)})
        cm = sec.get("comm") or {}
        acts_total += float(cm.get("acts", 0.0))
        delay_max = max(delay_max, float(cm.get("delay_s", 0.0)))
        for r, v in (cm.get("per_peer") or {}).items():
            per_peer[r] = max(per_peer.get(r, 0.0), float(v))
            delay_max = max(delay_max, float(v))
    # total activations x the best-informed per-frame delay estimate.
    # Direction estimates of one symmetric link legitimately diverge
    # (an accepted clock sample may have probed an idle or a congested
    # loop), so take the pessimistic direction, and scale by the
    # measured load factor: a delivery during a BUSY pipeline pays
    # wire + busy-loop dispatch + deliver/schedule — on the traced
    # rtt leg ~2x the idle-link one-way latency the clock probe
    # measures.  Deliberately an UPPER estimate: the telescoping
    # remainder clamp bounds it by what exec/queue leave, so on
    # comm-dominated runs comm converges to the true residual while
    # traffic-free windows stay at zero
    comm_s = acts_total * delay_max * 2.0
    anomalies.sort(key=lambda e: e.get("ts", 0.0))
    return {"recs": recs, "strag": strag,
            "anomalies": anomalies[-32:],
            "comm_s": comm_s, "per_peer_delay_s": per_peer,
            "window_s": window}


def telescope(elapsed: float, exec_s: float, queue_s: float,
              comm_s: float) -> dict:
    """Telescoping decomposition with a trust hierarchy: exec and
    queue are MEASURED (sampled per-task stamps — trusted first),
    comm is an ESTIMATE (scrape-time activation count x per-frame
    delay — capped into whatever the measured buckets leave), and
    idle is the INFERRED remainder.  The buckets ALWAYS sum to
    elapsed (the property the offline ``critpath.attribute``
    guarantees by construction); on wide DAGs, where cumulative task
    time legitimately exceeds the window, exec+queue scale down
    proportionally and comm/idle go to zero (documented
    approximation: the split is exact on critical-chain-dominated
    runs, a proportional share elsewhere)."""
    exec_s = max(0.0, exec_s)
    queue_s = max(0.0, queue_s)
    comm_s = max(0.0, comm_s)
    if elapsed <= 0.0:
        return {"exec": 0.0, "queue": 0.0, "comm": 0.0, "idle": 0.0,
                "elapsed": 0.0, "coverage": 0.0}
    eq = exec_s + queue_s
    if eq > elapsed:
        f = elapsed / eq
        exec_s, queue_s, comm_s, idle = exec_s * f, queue_s * f, \
            0.0, 0.0
    else:
        comm_s = min(comm_s, elapsed - eq)
        idle = elapsed - eq - comm_s
    covered = exec_s + queue_s + comm_s
    return {"exec": round(exec_s, 6), "queue": round(queue_s, 6),
            "comm": round(comm_s, 6), "idle": round(idle, 6),
            "elapsed": round(elapsed, 6),
            "coverage": round(min(1.0, covered / elapsed), 4)}


def _bucket_sums(rows: List[dict]) -> Tuple[float, float]:
    """(exec_s, queue_s) estimates over merged rows: sampled mean x
    exact done count per class.  Split mode contributes a real
    exec/queue separation; single-hook mode folds both into the
    sojourn, which lands in exec (documented: 'exec' then reads
    ready->complete)."""
    exec_s = queue_s = 0.0
    for row in rows:
        done = row["done"]
        ex, qu, lat = row.get("exec"), row.get("queue"), row.get("lat")
        q = (qu["sum"] / qu["n"]) * done \
            if qu is not None and qu["n"] else None
        if q is not None:
            queue_s += q
        if ex is not None and ex["n"]:
            exec_s += (ex["sum"] / ex["n"]) * done
        elif lat is not None and lat["n"]:
            # single-hook sojourn: subtract the queue share when the
            # split hook measured one, else the whole sojourn is exec
            sojourn = (lat["sum"] / lat["n"]) * done
            exec_s += max(0.0, sojourn - (q or 0.0))
    return exec_s, queue_s


# -- per-pool class totals (progress + ETA) ---------------------------------

def class_totals(tp, cap: Optional[int] = None) -> Optional[Dict[str,
                                                                 int]]:
    """Per-class task totals of a parameterized pool, enumerated once
    and cached on the pool.  Returns None for dynamic pools (totals
    unknowable before insertion stops) or spaces past the enumeration
    cap."""
    if tp is None:
        return None
    cached = getattr(tp, "_liveattr_totals", ...)
    if cached is not ...:
        return cached
    totals: Optional[Dict[str, int]] = {}
    cap = int(params.get("liveattr_enum_max", 100000)) \
        if cap is None else cap
    try:
        from parsec_tpu.core.taskpool import Compound, DynamicTaskpool
        pools = tp.pools if isinstance(tp, Compound) else [tp]
        seen = 0
        for pool in pools:
            if isinstance(pool, DynamicTaskpool):
                totals = None
                break
            for tc in pool.task_classes.values():
                n = 0
                for _ in tc.iter_space(pool.globals):
                    n += 1
                    seen += 1
                    if seen > cap:
                        raise OverflowError
                totals[tc.name] = totals.get(tc.name, 0) + n
    except OverflowError:
        totals = None
    except Exception:
        totals = None
    tp._liveattr_totals = totals
    return totals


def eta_seconds(class_rows: List[dict], pending_total: int,
                n_chips: int, done_total: int = 0,
                window_s: float = 0.0) -> Optional[float]:
    """Completion quote: remaining-task counts x live class profiles
    through the calibrated dagsim list-scheduling model.  ``class_rows``
    carry {"cls", "pending", "mean_s"[, "done"]}; classes with no
    profile yet borrow the across-class mean.  Returns None with
    nothing to go on.

    CALIBRATION: the class profiles give the relative cost mix, but
    their absolute scale can be off in either direction — a
    single-hook sojourn mean double-counts queueing (dagsim models
    queueing itself; verified 37x over on a deep-queued pool), and a
    split-mode body mean ignores comm/idle overhead.  When the
    observed completion rate is available (``done_total`` tasks over
    the ``window_s`` activity window), every class duration scales by
    one factor so the model's implied steady throughput matches the
    measured one — the quote then extrapolates what the gang actually
    sustains, with dagsim handling the mix and the tail."""
    # profile means come from EVERY observed class, pending or not —
    # a dynamic pool (unknown per-class totals, all pending None/0)
    # must still quote off its profiles + the aggregate remaining
    known = [r["mean_s"] for r in class_rows
             if r.get("mean_s", 0.0) > 0.0]
    if not known:
        return None
    fallback = sum(known) / len(known)
    rows = [dict(r) for r in class_rows if r.get("pending", 0) > 0]
    for r in rows:
        if r.get("mean_s", 0.0) <= 0.0:
            r["mean_s"] = fallback
    listed = sum(r["pending"] for r in rows)
    if pending_total > listed:
        # tasks outside the per-class rows (unknown totals): one
        # synthetic class at the blended duration — appended BEFORE
        # calibration so it scales with everything else
        rows.append({"cls": "__rest__",
                     "pending": pending_total - listed,
                     "mean_s": fallback})
    if done_total > 0 and window_s > 0.0:
        w = [(r.get("done", 0), r["mean_s"]) for r in rows]
        wsum = sum(d for d, _m in w)
        model_mean = (sum(d * m for d, m in w) / wsum) if wsum \
            else fallback
        target_mean = max(1, int(n_chips)) * window_s / done_total
        if model_mean > 0:
            f = target_mean / model_mean
            for r in rows:
                r["mean_s"] *= f
    from parsec_tpu.parallel.dagsim import SimDag, simulate
    budget = max(8, int(params.get("liveattr_sim_tasks", 512)))
    total = sum(r["pending"] for r in rows)
    if total <= 0:
        return 0.0
    dag = SimDag()
    chip = 0
    for r in rows:
        pend = r["pending"]
        nodes = max(1, min(pend, int(round(budget * pend / total))))
        work = pend * r["mean_s"]
        for i in range(nodes):
            key = (r["cls"], i)
            dag.nodes[key] = {"tc": r["cls"], "locals": {},
                              "chip": chip, "prio": 0,
                              "dur": work / nodes}
            chip += 1
    n_chips = max(1, int(n_chips))
    try:
        return round(simulate(dag, n_chips)["makespan_s"], 6)
    except Exception:
        return round(sum(r["pending"] * r["mean_s"] for r in rows)
                     / n_chips, 6)


# -- the status document ----------------------------------------------------

def _class_entry(row: dict, total: Optional[int],
                 completed: bool = False) -> dict:
    done = row["done"]
    if total is not None:
        # done is a stride-scaled estimate (exact at stride 1 / split
        # mode): clamp into the enumerated space, and snap a COMPLETED
        # pool's count to its total
        done = total if completed else min(done, total)
    inflight = max(0, row["sel"] - done) if row["sel"] else 0
    out = {"done": done, "inflight": inflight,
           "pending": (max(0, total - done - inflight)
                       if total is not None else None),
           "lat": _finish_profile(row.get("lat"))}
    for k in ("queue", "exec"):
        p = _finish_profile(row.get(k))
        if p is not None:
            out[k] = p
    return out


def _job_entry(job, merged: dict, comm_total: float,
               done_total: int, n_chips: int) -> dict:
    jid = job.job_id
    rows = [r for (j, _c), r in merged["recs"].items() if j == jid]
    totals = class_totals(job.taskpool)
    completed = job.taskpool is not None \
        and bool(getattr(job.taskpool, "completed", False))
    classes = {}
    pend_rows = []
    for r in sorted(rows, key=lambda x: x["cls"]):
        tot = totals.get(r["cls"]) if totals else None
        ent = _class_entry(r, tot, completed)
        classes[r["cls"]] = ent
        # the ETA's duration model prefers the split-mode BODY profile
        # (exec) over the sojourn; either way the throughput
        # calibration in eta_seconds sets the absolute scale
        prof = ent.get("exec") or ent.get("lat") or {}
        pend_rows.append({"cls": r["cls"],
                          "pending": ent["pending"] or 0,
                          "done": ent["done"],
                          "mean_s": prof.get("mean_s", 0.0)})
    if totals:
        for cls, tot in totals.items():
            if cls not in classes and tot > 0:
                # class never sampled: a completed pool's count snaps
                # to the enumerated total, a running one shows pending
                classes[cls] = {"done": tot if completed else 0,
                                "inflight": 0,
                                "pending": 0 if completed else tot,
                                "lat": None}
                if not completed:
                    pend_rows.append({"cls": cls, "pending": tot,
                                      "mean_s": 0.0})
    done = sum(ent["done"] for ent in classes.values())
    tp = job.taskpool
    remaining = max(0, int(getattr(tp, "nb_tasks", 0) or 0)) \
        if tp is not None and not getattr(tp, "completed", False) else 0
    status = job.status().name
    now = time.time()
    if job.started_at is None:
        elapsed = 0.0
    else:
        end = job.finished_at if job.finished_at is not None else now
        elapsed = max(0.0, end - job.started_at)
    exec_s, queue_s = _bucket_sums(rows)
    comm_s = comm_total * (done / done_total) if done_total else 0.0
    att = telescope(elapsed, exec_s, queue_s, comm_s)
    stragglers = [e for e in merged["anomalies"]
                  if e.get("job") == jid]
    eta = None
    if status == "RUNNING" and remaining:
        window = max((r.get("window_s", 0.0) for r in rows),
                     default=0.0)
        eta = eta_seconds(pend_rows, remaining, n_chips,
                          done_total=done, window_s=window)
    return {"job": jid, "name": job.name, "status": status,
            "elapsed_s": round(elapsed, 6),
            "progress": {"done": done,
                         "remaining": remaining,
                         "classes": classes},
            "attribution": att,
            "stragglers": stragglers,
            "eta_s": eta,
            "eta_method": None if eta is None else "dagsim"}


def build_status(context, service=None,
                 sections: Optional[Dict[int, dict]] = None,
                 health_sections: Optional[Dict[int, dict]] = None) -> dict:
    """Assemble the status document from merged per-rank sections.
    Degrades rather than fails: a job whose pieces cannot be read
    still appears with what is known.  ``health_sections`` are the
    per-rank ``__health__`` records riding the same pull; merged
    (prof/health.merge_health) into the document's ``health`` block."""
    merged = merge_sections(sections or {})
    done_total = sum(r["done"] for r in merged["recs"].values())
    comm_total = merged["comm_s"]
    n_chips = max(1, context.nranks) * max(1, len(context.streams))
    jobs = []
    if service is not None:
        for job in service.jobs():
            try:
                jobs.append(_job_entry(job, merged, comm_total,
                                       done_total, n_chips))
            except Exception as exc:   # degrade, never drop the scrape
                jobs.append({"job": job.job_id, "name": job.name,
                             "status": job.status().name,
                             "error": f"{type(exc).__name__}: {exc}"})
    # context-wide aggregate (covers batch pools with no job id)
    rows = list(merged["recs"].values())
    exec_s, queue_s = _bucket_sums(rows)
    agg_elapsed = merged["window_s"]
    agg = {
        "done": done_total,
        "classes": {r["cls"]: _class_entry(r, None)
                    for r in sorted(rows, key=lambda x: x["cls"])},
        "attribution": telescope(agg_elapsed, exec_s, queue_s,
                                 comm_total),
    }
    doc = {"ts": time.time(),
           "rank": context.rank,
           "ranks": sorted(sections or {context.rank: None}),
           "jobs": jobs,
           "aggregate": agg,
           "stragglers": merged["anomalies"],
           "stragglers_total": sum(merged["strag"].values()),
           "comm": {"per_peer_delay_s": merged["per_peer_delay_s"]}}
    if health_sections:
        try:
            from parsec_tpu.prof.health import merge_health
            doc["health"] = merge_health(health_sections)
        except Exception:   # degrade, never drop the scrape
            pass
    if service is not None:
        try:
            doc["service"] = service.stats()
        except Exception:
            pass
    return doc


def cluster_status(context, service=None, aggregate: bool = True,
                   timeout: float = 2.0) -> dict:
    """One status scrape: this rank's section plus — on a multi-rank
    context — every live peer's, extracted from the SAME TAG_METRICS
    pull the /metrics scrape uses (each rank's metrics snapshot
    carries its liveattr section as one extra sample record; zero new
    wire tags)."""
    m = getattr(context, "metrics", None)
    la = getattr(m, "_la", None) if m is not None else None
    hm = getattr(m, "_health", None) if m is not None else None
    sections: Dict[int, dict] = {}
    health_sections: Dict[int, dict] = {}
    if la is not None:
        sections[context.rank] = la.section()
    if hm is not None:
        try:
            hm.refresh()
            health_sections[context.rank] = hm.section()
        except Exception:
            pass
    comm = getattr(context, "comm", None)
    ce = getattr(comm, "ce", None) if comm is not None else None
    if aggregate and ce is not None and context.nranks > 1:
        try:
            for rank, samples in ce.gather_metrics(
                    timeout=timeout).items():
                for s in samples:
                    if s.get("t") != "section":
                        continue
                    if s.get("n") == "__liveattr__":
                        sections[int(rank)] = s.get("doc") or {}
                    elif s.get("n") == "__health__":
                        health_sections[int(rank)] = s.get("doc") or {}
        except Exception:   # degrade to the local view, never fail
            pass
    return build_status(context, service, sections, health_sections)
