"""Cross-rank causal DAG: trace merge, critical path, makespan buckets.

The merge-and-attribute half of the causal trace pipeline (the role the
reference fills with OTF2 + external analyzers; here the runtime's own
events — prof/causal.py — carry enough structure to answer "where did
the makespan go" directly):

1. :func:`merge_traces` loads one ``.ptt`` per rank, aligns every
   timestamp onto rank 0's clock using the per-peer offsets the
   TAG_CLOCK ping exchange recorded into each trace header, and tags
   rows with their rank.
2. :func:`build_dag` reconstructs the weighted cross-rank task DAG:
   nodes are task execution intervals (joined with their queue-wait
   spans by object id), intra-rank edges come from ``dep_edge`` events,
   and cross-rank edges from ``comm_send`` -> ``dep_deliver`` pairs
   matched on the frame correlation id (with the arrival timestamp as
   the edge's delivery time).
3. :func:`critical_path` walks backward from the last-finishing task,
   at each step following the *last-arriving input* — the predecessor
   whose completion (or whose frame's delivery) actually gated the
   task's start.
4. :func:`attribute` decomposes the makespan along that path into
   exec / queue / comm / idle buckets; by construction the buckets sum
   to the measured makespan (clamping only absorbs residual clock
   noise), which is the property the 2-rank acceptance test checks.

CLI::

    python -m parsec_tpu.prof.critpath rank0.ptt rank1.ptt [--json]
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from parsec_tpu.prof.causal import SPECIAL_CLASSES

#: event classes that are causal metadata, not task execution — ONE
#: source of truth (the tracer that writes them)
_SPECIAL = set(SPECIAL_CLASSES)


def _is_exec_name(name: str) -> bool:
    return name not in _SPECIAL and not name.startswith("dev:")


def _rank_of(meta: dict) -> Optional[int]:
    try:
        return int(meta["info"]["rank"])
    except (KeyError, TypeError, ValueError):
        return None


def _offsets_of(meta: dict) -> Dict[int, float]:
    raw = meta.get("info", {}).get("clock_offsets")
    if not raw:
        return {}
    try:
        return {int(r): float(o) for r, o in json.loads(raw).items()}
    except (TypeError, ValueError):
        return {}


def merge_traces(paths: List[str]):
    """Load per-rank ``.ptt`` traces, align clocks, return
    ``(df, metas)`` — one DataFrame with ``rank`` column and timestamps
    on the reference (lowest-rank) timeline.

    Alignment: for rank r, prefer r's own measured offset to the
    reference (``offset = clock_ref - clock_r`` -> ``ts + offset``);
    fall back to the reference's measurement of r (negated); traces
    from the same host share CLOCK_MONOTONIC, so a missing table
    degrades to zero shift, not garbage."""
    import pandas as pd
    from parsec_tpu.prof.reader import read_trace
    loaded = []
    for p in paths:
        meta, df = read_trace(p)
        loaded.append([_rank_of(meta), meta, df])
    # traces without a rank header (task-profiler-only dumps) or with
    # colliding rank claims still get DISTINCT rank ids: every profile
    # numbers event_ids from 1, so merging two files under one rank
    # would falsely pair START/END rows across them
    taken = {r for r, _m, _d in loaded if r is not None}
    spare = (r for r in range(len(loaded) + len(taken) + 1)
             if r not in taken)
    seen: set = set()
    for ent in loaded:
        if ent[0] is None or ent[0] in seen:
            ent[0] = next(spare)
        seen.add(ent[0])
    loaded.sort(key=lambda e: e[0])
    ref = loaded[0][0]
    ref_offsets = _offsets_of(loaded[0][1])
    frames = []
    metas = {}
    for rank, meta, df in loaded:
        metas[rank] = meta
        shift = 0.0
        if rank != ref:
            own = _offsets_of(meta)
            if ref in own:
                shift = own[ref]
            elif rank in ref_offsets:
                shift = -ref_offsets[rank]
        df = df.copy()
        df["rank"] = rank
        if shift:
            df["ts"] = df["ts"] + shift
        frames.append(df)
    return pd.concat(frames, ignore_index=True), metas


def build_dag(df):
    """Reconstruct the weighted cross-rank DAG from a merged frame.

    Returns ``(tasks, preds, ready)``, all keyed by node identity
    (rank, taskpool_id, oid) — the taskpool matters: two pools' tasks
    legitimately share key hashes (a warmup pool rerunning the same
    task names), and colliding them would fabricate causal edges:

    - ``tasks``: node -> {name, rank, oid, start, end}
    - ``preds``: node -> list of (pred node, edge) where edge is None
      for a local dep or {"send", "arrive", "nbytes"} for a cross-rank
      flow edge
    - ``ready``: node -> queue-wait begin timestamp
    """
    from parsec_tpu.prof.reader import intervals
    tasks: Dict[Tuple[int, int, int], dict] = {}
    ready: Dict[Tuple[int, int, int], float] = {}
    preds: Dict[Tuple[int, int, int], List] = {}
    iv = intervals(df) if len(df) else df
    if len(iv):
        for row in iv.itertuples():
            node = (int(row.rank), int(row.taskpool_id),
                    int(row.object_id))
            if row.name == "queue_wait":
                # several readiness episodes (AGAIN loops): keep the last
                ready[node] = max(ready.get(node, 0.0),
                                  float(row.ts_begin))
            elif _is_exec_name(row.name):
                cur = tasks.get(node)
                if cur is None or row.ts_end > cur["end"]:
                    tasks[node] = {"name": row.name, "rank": node[0],
                                   "tp": node[1], "oid": node[2],
                                   "start": float(row.ts_begin),
                                   "end": float(row.ts_end)}
    # local dependency edges (producer and successor share the pool)
    for row in df[df["name"] == "dep_edge"].itertuples():
        info = row.info or {}
        dst = info.get("dst")
        if dst is None:
            continue
        rank, tpid = int(row.rank), int(row.taskpool_id)
        preds.setdefault((rank, tpid, int(dst)), []).append(
            ((rank, tpid, int(row.object_id)), None))
    # cross-rank flow edges: comm_send matched to dep_deliver by corr
    sends: Dict[Tuple[int, int], Any] = {}
    for row in df[df["name"] == "comm_send"].itertuples():
        info = row.info or {}
        corr = info.get("corr")
        if corr is not None:
            sends[tuple(corr)] = row
    for row in df[df["name"] == "dep_deliver"].itertuples():
        info = row.info or {}
        corr = info.get("corr")
        snd = sends.get(tuple(corr)) if corr is not None else None
        if snd is None or not snd.object_id:
            continue
        sinfo = snd.info or {}
        edge = {"send": float(snd.ts), "arrive": float(row.ts),
                "nbytes": sinfo.get("nbytes", 0)}
        # a tree-forwarded frame is SENT by an intermediate rank but its
        # oid names the producer's task (whose exec interval lives in
        # the producer's trace, with the producer's per-process hash):
        # the edge's source is src_rank (the activation root) when the
        # frame carries one
        src_rank = sinfo.get("src_rank", int(snd.rank))
        preds.setdefault(
            (int(row.rank), int(row.taskpool_id),
             int(row.object_id)), []).append(
            ((int(src_rank), int(snd.taskpool_id),
              int(snd.object_id)), edge))
    return tasks, preds, ready


def matched_flows(df) -> Tuple[int, int, int]:
    """(sends, recvs, matched-corr pairs) of comm frames in a merged
    trace — the 'every activation's send has its recv' check."""
    s = {tuple(r.info["corr"]) for r in
         df[df["name"] == "comm_send"].itertuples()
         if r.info and r.info.get("corr")}
    r = {tuple(x.info["corr"]) for x in
         df[df["name"] == "comm_recv"].itertuples()
         if x.info and x.info.get("corr")}
    return len(s), len(r), len(s & r)


def critical_path(tasks, preds):
    """The causal chain ending at the last-finishing task: step
    backward choosing, at each node, the predecessor whose completion
    (local) or frame delivery (remote) arrived LAST — the input that
    actually gated the start (queue-ready times enter at the
    attribution stage, not here).  Returns [(node_dict, in_edge), ...]
    in execution order; the first element's in_edge is None."""
    if not tasks:
        return []
    cur = max(tasks, key=lambda n: tasks[n]["end"])
    path = []
    seen = set()
    while cur is not None and cur not in seen:
        seen.add(cur)
        best, best_t, best_edge = None, None, None
        for pred, edge in preds.get(cur, ()):
            if pred not in tasks or pred in seen:
                continue
            t = edge["arrive"] if edge is not None else tasks[pred]["end"]
            if best_t is None or t > best_t:
                best, best_t, best_edge = pred, t, edge
        # each node pairs with its IN-edge — the input that gated it
        path.append((tasks[cur], best_edge if best is not None else None))
        cur = best
    path.reverse()
    return path


def attribute(path, tasks, ready) -> Dict[str, Any]:
    """Decompose the trace's makespan into exec / queue / comm / idle
    along the critical path.  Segments are clamped non-negative (clock
    noise); ``coverage`` reports sum(buckets)/makespan."""
    if not tasks:
        return {"makespan": 0.0, "buckets": {}, "path": [],
                "coverage": 0.0}
    t0 = min(t["start"] for t in tasks.values())
    tend = max(t["end"] for t in tasks.values())
    makespan = tend - t0
    buckets = {"exec": 0.0, "queue": 0.0, "comm": 0.0, "idle": 0.0}
    steps = []
    prev = None
    for node, edge in path:
        key = (node["rank"], node["tp"], node["oid"])
        rdy = ready.get(key, node["start"])
        rdy = min(max(rdy, t0), node["start"])
        if prev is None:
            base = t0
        else:
            base = min(prev["end"], rdy)
        if edge is not None:
            arrive = min(max(edge["arrive"], base), rdy)
            buckets["comm"] += arrive - base
            buckets["idle"] += rdy - arrive
        else:
            buckets["idle"] += rdy - base
        buckets["queue"] += node["start"] - rdy
        buckets["exec"] += node["end"] - node["start"]
        steps.append({"task": node["name"], "rank": node["rank"],
                      "start": node["start"] - t0,
                      "end": node["end"] - t0,
                      "via": "comm" if edge is not None else "local"})
        prev = node
    total = sum(buckets.values())
    return {"makespan": makespan,
            "buckets": {k: round(v, 6) for k, v in buckets.items()},
            "coverage": round(total / makespan, 4) if makespan else 0.0,
            "ntasks": len(tasks),
            "path": steps}


def attribution(paths: List[str]) -> Dict[str, Any]:
    """One call from trace files to the attribution summary (what
    bench.py embeds in its JSON line under PARSEC_BENCH_TRACE=1)."""
    df, metas = merge_traces(paths)
    tasks, preds, ready = build_dag(df)
    path = critical_path(tasks, preds)
    out = attribute(path, tasks, ready)
    s, r, m = matched_flows(df)
    out["flows"] = {"sends": s, "recvs": r, "matched": m}
    out["nranks"] = len(metas)
    return out


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="merge per-rank .ptt traces, extract the critical "
                    "path, attribute the makespan")
    ap.add_argument("traces", nargs="+", help="one .ptt per rank")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object")
    args = ap.parse_args(argv)
    out = attribution(args.traces)
    if args.json:
        print(json.dumps(out))
        return 0
    b = out["buckets"]
    ms = out["makespan"]
    print(f"makespan: {ms * 1e3:.3f} ms over {out['nranks']} rank(s), "
          f"{out['ntasks']} tasks "
          f"(bucket coverage {out['coverage']:.1%})")
    for k in ("exec", "queue", "comm", "idle"):
        v = b.get(k, 0.0)
        share = v / ms if ms else 0.0
        print(f"  {k:>5}: {v * 1e3:9.3f} ms  ({share:6.1%})")
    f = out["flows"]
    print(f"flow edges: {f['matched']} matched of {f['sends']} sends / "
          f"{f['recvs']} recvs")
    print("critical path:")
    for s in out["path"]:
        print(f"  [{s['via']:>5}] rank {s['rank']} {s['task']:<24} "
              f"{s['start'] * 1e3:9.3f} -> {s['end'] * 1e3:9.3f} ms")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
