"""Flight recorder: a bounded ring of causal events, dumped on failure.

PR 4's causal tracer answers "what just happened" beautifully but costs
~30% tasks/s and must be armed BEFORE the incident.  The production
pattern (the ROADMAP's resident service) is the inverse: a cheap,
continuously-overwritten ring of the last N causal-class events —
comm flow edges (send/recv/deliver with correlation ids), device
dispatch spans, DTD lane transitions — that is dumped automatically
AFTER a failure is detected:

* ``Context.record_pool_error`` / PeerFailedError containment /
  the hang autopsy / a job-SLO breach (prof/metrics.py) all call
  ``Context.telemetry_incident``, which lands here;
* the dump writes this rank's ring as a standard ``.ptt`` (rank +
  TAG_CLOCK offsets in the header, exactly like a causal trace) into
  the incident bundle directory, appends a manifest line, and
  broadcasts TAG_FLIGHT so live peers dump their rings into the same
  bundle — ``prof/critpath.merge_traces`` (and therefore
  ``tools/trace2chrome.py --merge``) then opens the bundle unchanged;
* the event encodings ARE prof/causal.py's: FlightRecorder subclasses
  CausalTracer, swapping the unbounded Profile for a ring-backed one
  and installing only the cheap hooks (no queue-wait stamping, no exec
  intervals; dep/dtd points ride a sampling gate) so the armed steady
  state stays inside the premerge <=5% telemetry gate.

Arm with ``PARSEC_MCA_FLIGHTREC_ENABLED=1`` (knobs: ring size, bundle
directory, recorded classes, sampling, re-dump interval).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from parsec_tpu.prof.causal import COMM_STREAM, CausalTracer
from parsec_tpu.prof.profiling import EV_END, EV_START, EventClass, Profile
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import warning

params.register("flightrec_enabled", 0,
                "arm the crash-dump flight recorder on every Context: a "
                "bounded ring of causal-class events (comm flow edges, "
                "device spans, DTD lane ops) continuously overwritten "
                "and dumped to a merged-openable incident bundle when "
                "containment, the hang autopsy, or an SLO breach fires")
params.register("flightrec_ring", 65536,
                "flight-recorder ring capacity in EVENTS (bounded "
                "memory: oldest events are overwritten; at comm-frame "
                "rates the default holds the last tens of seconds)")
params.register("flightrec_dir", "",
                "incident bundle directory shared by every rank "
                "(default: <tmpdir>/parsec-flightrec); each incident "
                "dump writes rank<N>.ptt here plus a line in "
                "incidents.jsonl")
params.register("flightrec_classes", "comm,device,dtd",
                "event classes the recorder captures: comm (send/recv/"
                "deliver flow edges), device (dispatch->done spans), "
                "dtd (lane/surrogate points), deps (local dep_edge "
                "points; off by default — the densest class)")
params.register("flightrec_sample", 1,
                "sampling stride for the dense point classes (dtd, "
                "deps): 1 records every event, N one in N; comm flow "
                "edges are never sampled so send/deliver pairs match "
                "in the merged bundle")
params.register("flightrec_min_interval_s", 30.0,
                "minimum seconds between incident dumps on one rank "
                "(a failure storm re-dumps at most this often; the "
                "first dump of each quiet period wins)")


class _RingStream:
    """StreamBuffer-shaped writer appending into the shared ring."""

    __slots__ = ("stream_id", "name", "_ring", "_now")

    def __init__(self, stream_id: int, name: str, ring: deque):
        self.stream_id = stream_id
        self.name = name
        self._ring = ring
        self._now = time.perf_counter

    def trace(self, key: int, flags: int, taskpool_id: int, event_id: int,
              object_id: int = 0, info: Any = None,
              timestamp: Optional[float] = None) -> None:
        # deque.append with maxlen is a single atomic op under the GIL:
        # the ring takes no lock on the hot path
        self._ring.append((self.stream_id, key, flags, taskpool_id,
                           event_id, object_id,
                           timestamp if timestamp is not None
                           else self._now(), info))

    def interval(self, key: int, taskpool_id: int, event_id: int,
                 object_id: int, t_begin: float) -> None:
        self._ring.append((self.stream_id, key, EV_START, taskpool_id,
                           event_id, object_id, t_begin, None))
        self._ring.append((self.stream_id, key, EV_END, taskpool_id,
                           event_id, object_id, self._now(), None))


class RingProfile(Profile):
    """A Profile whose streams write into ONE bounded ring; ``dump``
    replays the ring snapshot through a real Profile so the on-disk
    format (and every reader: prof/reader, critpath, trace2chrome) is
    identical to a causal trace."""

    def __init__(self, maxlen: int, hr_id: str = "flightrec"):
        super().__init__(hr_id)
        self._ring: deque = deque(maxlen=max(256, maxlen))

    def stream(self, stream_id: int, name: str = ""):
        with self._lock:
            sb = self._streams.get(stream_id)
            if sb is None:
                sb = _RingStream(stream_id, name or f"stream-{stream_id}",
                                 self._ring)
                self._streams[stream_id] = sb
            return sb

    def dump(self, path: str) -> str:
        events = list(self._ring)          # one consistent snapshot
        with self._lock:
            dico = list(self._dict.values())
            names = {sid: sb.name for sid, sb in self._streams.items()}
            info = dict(self._info)
        p = Profile(self.hr_id)
        p._info.update(info)
        p._dict = {ec.name: EventClass(ec.name, ec.key, ec.attributes)
                   for ec in dico}
        for sid, key, flags, tpid, eid, oid, ts, evinfo in events:
            sb = p.stream(sid, names.get(sid, ""))
            sb.events.append((key, flags, tpid, eid, oid, ts, evinfo))
        return p.dump(path)

    def __len__(self) -> int:
        return len(self._ring)


class FlightRecorder(CausalTracer):
    """CausalTracer encodings over a ring profile, with only the cheap
    hooks installed and an ``incident`` dump path."""

    def __init__(self, context):
        ring = int(params.get("flightrec_ring", 65536))
        super().__init__(RingProfile(ring), rank=context.rank)
        self.context = context
        self.classes = {c.strip() for c in
                        str(params.get("flightrec_classes",
                                       "comm,device,dtd")).split(",")
                        if c.strip()}
        self._sample = max(1, int(params.get("flightrec_sample", 1)))
        self._sn = 0
        raw_dir = str(params.get("flightrec_dir", "") or "").strip()
        self.bundle_dir = raw_dir or os.path.join(
            tempfile.gettempdir(), "parsec-flightrec")
        self._min_interval = float(params.get("flightrec_min_interval_s",
                                              30.0))
        self._inc_lock = threading.Lock()
        self._last_inc = -float("inf")   # guarded-by: _inc_lock
        self.incidents = 0
        self.last_bundle: Optional[str] = None
        #: comm-engine counter baseline for the incident WINDOW: the
        #: bundle records stats deltas since arm (or the previous
        #: dump), not lifetime totals — a straggler incident carries
        #: its own comm context
        self._comm_base: Optional[Dict[str, float]] = None
        self._comm_base_at = time.monotonic()

    # -- lifecycle (override: only the cheap hooks) ----------------------
    def install(self, context) -> "FlightRecorder":
        self.rank = context.rank
        self.context = context
        context._flightrec = self
        context._recompute_ready_stamp()   # device-span gate
        try:
            # surface a misconfigured bundle dir at ARM time: an
            # incident pointing the autopsy at an unwritable path
            # would only warn after the fact
            os.makedirs(self.bundle_dir, exist_ok=True)
        except OSError as exc:
            warning("flight recorder: bundle dir %s is not writable "
                    "(%s) — incident dumps WILL fail; fix "
                    "flightrec_dir", self.bundle_dir, exc)
        if "device" in self.classes:
            context.pins_register("device_dispatch", self._dev_dispatch)
            context.pins_register("device_done", self._dev_done)
        if "deps" in self.classes:
            context.pins_register("deliver_dep", self._deliver_dep)
        self.attach_comm(context.comm)
        self._comm_base = self._comm_scalars()
        self._comm_base_at = time.monotonic()
        return self

    def uninstall(self, context) -> None:
        if getattr(context, "_flightrec", None) is self:
            context._flightrec = None
            context._recompute_ready_stamp()
        if "device" in self.classes:
            context.pins_unregister("device_dispatch", self._dev_dispatch)
            context.pins_unregister("device_done", self._dev_done)
        if "deps" in self.classes:
            context.pins_unregister("deliver_dep", self._deliver_dep)
        comm = getattr(context, "comm", None)
        if comm is not None and getattr(comm, "flightrec", None) is self:
            comm.flightrec = None
        ce = getattr(comm, "ce", None) if comm is not None else None
        if ce is not None and ce.on_flight_dump == self._remote_dump:
            # a detached recorder must not answer TAG_FLIGHT dumps
            ce.on_flight_dump = None

    def attach_comm(self, comm) -> None:
        """Wire the comm layer (either install order: recorder first or
        RemoteDepEngine first — remote_dep.__init__ calls this too)."""
        if comm is None:
            return
        if "comm" in self.classes:
            comm.flightrec = self
        ce = getattr(comm, "ce", None)
        if ce is not None:
            ce.on_flight_dump = self._remote_dump

    # -- sampling gate for the dense point classes -----------------------
    def _sampled(self) -> bool:
        self._sn += 1            # racy under threads: approximate stride
        return self._sn % self._sample == 0

    def _deliver_dep(self, es, event, payload) -> None:
        if self._sampled():
            super()._deliver_dep(es, event, payload)

    def dtd_event(self, op: str, tile, lane, ver: int, val=None) -> None:
        if "dtd" in self.classes and self._sampled():
            super().dtd_event(op, tile, lane, ver, val)

    # -- incident dump ---------------------------------------------------
    def incident(self, reason: str, broadcast: bool = True) -> Optional[str]:
        """Dump this rank's ring into the bundle directory (rate-limited)
        and — when ``broadcast`` — ask live peers over TAG_FLIGHT to do
        the same, so the bundle merges into one clock-aligned timeline.

        The dump runs on its OWN non-daemon thread: containment often
        fires on the comm loop thread, and stalling that loop for file
        I/O would starve the very heartbeats whose failure is being
        recorded (peers could declare US dead mid-dump); non-daemon so
        a failing worker process still finishes the write before exit.
        The bundle path is deterministic, so it is returned (and kept
        as ``last_bundle``) immediately."""
        now = time.monotonic()
        with self._inc_lock:
            if now - self._last_inc < self._min_interval:
                return self.last_bundle
            self._last_inc = now
        self.last_bundle = self.bundle_dir
        t = threading.Thread(target=self._dump_async,
                             args=(reason, broadcast),
                             name="flightrec-dump", daemon=False)
        try:
            t.start()
        except RuntimeError:   # interpreter teardown: last-ditch inline
            self._dump_async(reason, broadcast)
        return self.bundle_dir

    def _dump_async(self, reason: str, broadcast: bool) -> None:
        try:
            self._dump(reason)
        except Exception as exc:   # the dump must never re-raise
            warning("flight recorder: dump failed: %s", exc)
            with self._inc_lock:
                # give the rate-limit window back: a transient write
                # failure must not suppress the NEXT incident's dump
                self._last_inc = -float("inf")
            return
        if broadcast:
            self._broadcast(reason)

    def _dump(self, reason: str) -> str:
        os.makedirs(self.bundle_dir, exist_ok=True)
        ctx = self.context
        if ctx is not None:
            self.finalize(ctx)     # rank + nranks + clock offsets header
            jr = getattr(ctx, "journal", None)
            if jr is not None:
                # the control-plane story lands NEXT TO the data-plane
                # ring: every incident bundle carries this rank's
                # protocol journal (journal-rank<N>.jsonl), so
                # tools/journal_audit.py reconstructs the recovery
                # rounds behind the incident from the same directory
                try:
                    jr.dump(self.bundle_dir)
                except OSError as exc:
                    warning("flight recorder: journal dump failed: %s",
                            exc)
        self.profile.add_information("flightrec_reason", reason)
        out = os.path.join(self.bundle_dir, f"rank{self.rank}.ptt")
        self.profile.dump(out)
        self._dump_health(reason)
        with open(os.path.join(self.bundle_dir, "incidents.jsonl"),
                  "a") as fh:
            fh.write(json.dumps({
                "rank": self.rank, "reason": reason,
                "wall": time.time(), "events": len(self.profile),
            }) + "\n")
        self.incidents += 1
        self.last_bundle = self.bundle_dir
        warning("flight recorder: rank %d dumped %d events to incident "
                "bundle %s (%s)", self.rank, len(self.profile),
                self.bundle_dir, reason)
        return self.bundle_dir

    def _comm_scalars(self) -> Dict[str, float]:
        """Numeric comm-engine counters (best-effort snapshot)."""
        ctx = self.context
        comm = getattr(ctx, "comm", None) if ctx is not None else None
        if comm is None:
            return {}
        try:
            st = comm.stats()
        except Exception:
            return {}
        return {k: float(v) for k, v in st.items()
                if isinstance(v, (int, float))
                and not isinstance(v, bool)}

    def _dump_health(self, reason: str) -> None:
        """Write ``health-rank<N>.json`` next to the ring: the health
        plane's per-rank score time-series (prof/health.py) plus the
        comm-engine stats DELTAS for the incident window — the two
        planes that say why the incident happened, not just what.
        Best-effort: neither plane being armed skips the file."""
        cur = self._comm_scalars()
        base = self._comm_base or {}
        now = time.monotonic()
        delta = {k: round(v - base.get(k, 0.0), 6)
                 for k, v in cur.items() if v != base.get(k, 0.0)}
        series: Dict[Any, Any] = {}
        scores: Dict[Any, Any] = {}
        m = getattr(self.context, "metrics", None) \
            if self.context is not None else None
        hm = getattr(m, "_health", None) if m is not None else None
        if hm is not None:
            try:
                series = hm.series_snapshot()
                scores = hm.snapshot()
            except Exception:
                pass
        if not cur and not series:
            return
        doc = {"rank": self.rank, "reason": reason, "wall": time.time(),
               "comm_window_s": round(now - self._comm_base_at, 3),
               "comm_delta": delta,
               "health": {str(r): ent for r, ent in scores.items()},
               "health_series": {str(r): pts
                                 for r, pts in series.items()}}
        try:
            with open(os.path.join(self.bundle_dir,
                                   f"health-rank{self.rank}.json"),
                      "w") as fh:
                json.dump(doc, fh)
        except OSError as exc:
            warning("flight recorder: health snapshot failed: %s", exc)
            return
        # rebase: the NEXT incident's window starts here
        self._comm_base = cur
        self._comm_base_at = now

    def _broadcast(self, reason: str) -> None:
        ctx = self.context
        comm = getattr(ctx, "comm", None) if ctx is not None else None
        ce = getattr(comm, "ce", None) if comm is not None else None
        if ce is None:
            return
        from parsec_tpu.comm.engine import TAG_FLIGHT
        for r in range(ce.nranks):
            if r == ce.rank or r in ce.dead_peers:
                continue
            try:
                ce.send_am(TAG_FLIGHT, r,
                           {"reason": f"rank {ce.rank}: {reason}"})
            except OSError:
                pass   # a dead peer cannot dump anyway

    def _remote_dump(self, reason: str) -> None:
        """TAG_FLIGHT handler target (engine.py posts it off-loop)."""
        self.incident(reason, broadcast=False)


def install_flight_recorder(context) -> FlightRecorder:
    return FlightRecorder(context).install(context)


# ---------------------------------------------------------------------------
# CLI: summarize an incident bundle
# ---------------------------------------------------------------------------

def summarize_bundle(path: str) -> Dict[str, Any]:
    """Merge a bundle's per-rank rings (clock-aligned) and report flow
    coverage — the programmatic half of ``trace2chrome --merge``."""
    import glob
    from parsec_tpu.prof.critpath import matched_flows, merge_traces
    traces = sorted(glob.glob(os.path.join(path, "rank*.ptt")))
    if not traces:
        raise FileNotFoundError(f"no rank*.ptt traces under {path!r}")
    df, metas = merge_traces(traces)
    if len(df) and "name" in df.columns:
        sends, recvs, matched = matched_flows(df)
    else:   # a ring with no events of interest dumps an empty trace
        sends = recvs = matched = 0
    incidents: List[dict] = []
    manifest = os.path.join(path, "incidents.jsonl")
    if os.path.exists(manifest):
        with open(manifest) as fh:
            incidents = [json.loads(line) for line in fh if line.strip()]
    return {"traces": traces, "ranks": sorted(metas), "events": len(df),
            "flows": {"sends": sends, "recvs": recvs, "matched": matched},
            "incidents": incidents}


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="summarize a flight-recorder incident bundle")
    ap.add_argument("bundle", help="incident bundle directory")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    out = summarize_bundle(args.bundle)
    if args.json:
        print(json.dumps(out))
        return 0
    print(f"bundle {args.bundle}: ranks {out['ranks']}, "
          f"{out['events']} events")
    f = out["flows"]
    print(f"flow edges: {f['matched']} matched of {f['sends']} sends / "
          f"{f['recvs']} recvs")
    for inc in out["incidents"]:
        print(f"  incident: rank {inc['rank']} — {inc['reason']} "
              f"({inc['events']} events)")
    print("open with: python tools/trace2chrome.py --merge "
          + " ".join(out["traces"]) + " -o incident.json")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
