"""Predictive health plane: continuous per-rank health scoring.

Every raw observability plane already exists — live attribution
(prof/liveattr.py), the protocol journal (prof/journal.py), per-peer
comm EWMAs (RemoteDepEngine.stats), heartbeat arrival tracking
(comm/engine.py hb_stats), the C chain's bailout counters — but
nothing consumes them continuously.  This module fuses them into ONE
number per rank: a health score in [0, 1] (1.0 = healthy), EWMA
smoothed, with a trend estimate and a bounded time-series, so the
serving fabric can drain a DEGRADING rank before the heartbeat
detector declares it dead (ROADMAP item "PREDICTIVE health").

Discipline (the same PAPI-SDE pattern as liveattr's comm bucket): the
monitor adds ZERO hot-path crossings.  Every signal below is a counter
or EWMA some other plane already maintains; :meth:`HealthMonitor.refresh`
reads them at SCRAPE time (rate-limited to ``health_interval_s``) and
folds penalties into per-rank scores:

* **self signals** (this rank's own degradation): straggler-counter
  growth and per-(job, class) sojourn drift (EWMA vs long-run mean)
  from the live attribution records; native-chain bailout-rate
  regressions (``load_schedext().bailout_stats``); transport
  backpressure growth (ring-full stalls, partial writes, eager
  downshifts); and unresolved recovery rounds / degraded retirements
  in the journal tail;
* **peer signals** (a peer degrading as seen from here): heartbeat
  inter-arrival inflation + jitter against the learned cadence
  baseline (``CommEngine.hb_stats``), current silence age as a
  fraction of ``comm_peer_timeout_s``, and per-peer comm-delay
  inflation (clock-probe rtt/2 + drain EWMA) against its baseline.

Export rides the existing surfaces only: ``parsec_rank_health{rank}``
gauges through RuntimeMetrics.samples, a ``__health__`` section record
on the TAG_METRICS pull (zero new wire tags — the liveattr section
precedent), a ``health`` block in the ``{"op": "status"}`` document
(:func:`merge_health` folds per-rank sections pessimistically), state
transitions in the protocol journal (``health_transition``), and
time-series snapshots in flight-recorder incident bundles.  The loop
is closed in service/fabric.py: quotes inflate against the gang's
minimum health, and a sustained below-threshold score triggers a
journaled pre-emptive drain audited by tools/journal_audit.py (H1).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from parsec_tpu.prof.metrics import counter_sample, gauge_sample
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose

params.register("health_enable", 1,
                "arm the predictive health plane on the metrics "
                "registry: per-rank 0..1 scores fused at scrape time "
                "from the straggler/journal/comm/bailout counters the "
                "other planes already maintain (0 disables)")
params.register("health_interval_s", 1.0,
                "minimum seconds between health folds: every scrape "
                "or fabric tick inside the window reuses the last "
                "fold (bounds the scrape-side cost)")
params.register("health_series", 120,
                "bounded per-rank score time-series length (the "
                "flight-recorder snapshot and drain evidence window)")
params.register("health_alpha", 0.3,
                "EWMA fold factor of the per-rank health score")
params.register("health_degraded", 0.75,
                "smoothed score below this enters state 'degraded'")
params.register("health_critical", 0.5,
                "smoothed score below this enters state 'critical' — "
                "the fabric's pre-emptive drain threshold")
params.register("health_hysteresis", 0.05,
                "margin above a threshold required to move back UP a "
                "state (flap damping on the transition journal)")


class _RankHealth:
    """Mutable per-rank scoring state (guarded-by: monitor lock)."""

    __slots__ = ("rank", "score", "ewma", "trend", "state", "since",
                 "series", "n")

    def __init__(self, rank: int, cap: int):
        self.rank = rank
        self.score = 1.0
        self.ewma = 1.0
        self.trend = 0.0
        self.state = "ok"
        self.since = time.monotonic()
        self.series: deque = deque(maxlen=cap)
        self.n = 0


def _clamp(x: float, lo: float = 0.0, hi: float = 1.0) -> float:
    return lo if x < lo else hi if x > hi else x


class HealthMonitor:
    """Scrape-time fusion of the existing observability planes into
    per-rank health scores.  Created by RuntimeMetrics.install (the
    liveattr precedent); every accessor is safe against a partially
    torn-down context — a broken signal source degrades that penalty
    to zero, never the scrape."""

    def __init__(self, metrics):
        self._metrics = metrics
        self._lock = threading.Lock()
        self._interval = float(params.get("health_interval_s", 1.0))
        self._alpha = float(params.get("health_alpha", 0.3))
        self._cap = max(8, int(params.get("health_series", 120)))
        self._thr_deg = float(params.get("health_degraded", 0.75))
        self._thr_crit = float(params.get("health_critical", 0.5))
        self._hyst = float(params.get("health_hysteresis", 0.05))
        self._ranks: Dict[int, _RankHealth] = {}
        self._last_fold = 0.0
        #: counter baselines (self signals fold as window deltas)
        self._strag_base = 0.0
        self._bail_base: Optional[float] = None
        self._bail_rate = 0.0
        self._bp_base: Optional[float] = None
        #: per-peer learned baselines (min-tracked: the healthy floor)
        self._hb_base: Dict[int, float] = {}
        self._delay_base: Dict[int, float] = {}
        self.folds = 0
        self.transitions = 0

    # -- signal reads (each best-effort, scrape time only) ---------------

    def _context(self):
        return getattr(self._metrics, "context", None)

    def _pen_stragglers(self) -> float:
        """Straggler-counter growth this window (liveattr counts)."""
        la = getattr(self._metrics, "_la", None)
        if la is None:
            return 0.0
        try:
            with la._lock:  # lint: ignore[PCL-HOT] (scrape-side read of liveattr's counters, rate-limited by health_interval_s)
                total = float(sum(la._strag_counts.values()))
        except Exception:
            return 0.0
        delta = max(0.0, total - self._strag_base)
        self._strag_base = total
        return _clamp(0.08 * delta, 0.0, 0.35)

    def _pen_sojourn_drift(self) -> float:
        """Per-(job, class) sojourn EWMA drifting above its own
        long-run mean — slowdown without (yet) any straggler event."""
        la = getattr(self._metrics, "_la", None)
        if la is None:
            return 0.0
        worst = 0.0
        try:
            with la._lock:  # lint: ignore[PCL-HOT] (scrape-side walk of liveattr's records, rate-limited)
                recs = list(la._recs.values())
            for rec in recs:
                with rec.lock:  # lint: ignore[PCL-HOT] (per-record scrape-side read, bounded by (job, class) count)
                    p = rec.lat
                    if p.n < 32 or p.sum <= 0.0:
                        continue
                    mean = p.sum / p.n
                    if mean > 0.0:
                        worst = max(worst, p.ewma / mean - 1.0)
        except Exception:
            return 0.0
        return _clamp(0.15 * max(0.0, worst - 0.5), 0.0, 0.3)

    def _pen_bailouts(self) -> float:
        """Native-chain bailout RATE regression: a steady bailout mix
        is the workload's shape; a step-up means classes started
        falling off the C chain."""
        try:
            from parsec_tpu.native import load_schedext
            se = load_schedext()
            if se is None:
                return 0.0
            total = float(sum(se.bailout_stats().values()))
        except Exception:
            return 0.0
        if self._bail_base is None:
            self._bail_base = total
            return 0.0
        delta = max(0.0, total - self._bail_base)
        self._bail_base = total
        prev = self._bail_rate
        self._bail_rate += 0.3 * (delta - self._bail_rate)
        if prev <= 0.0:
            return 0.0
        return _clamp(0.05 * max(0.0, delta / prev - 2.0), 0.0, 0.2)

    def _pen_backpressure(self, st: Dict[str, Any]) -> float:
        """Transport backpressure growth: ring-full stalls, partial
        writes, protocol eager downshifts."""
        total = 0.0
        for k in ("shm_ring_full_stalls", "partial_writes",
                  "eager_downshift"):
            try:
                total += float(st.get(k, 0) or 0)
            except Exception:
                pass
        if self._bp_base is None:
            self._bp_base = total
            return 0.0
        delta = max(0.0, total - self._bp_base)
        self._bp_base = total
        return _clamp(0.02 * delta, 0.0, 0.25)

    def _pen_journal(self) -> float:
        """Unresolved recovery rounds / degraded retirements in the
        journal tail: a rank mid-recovery is not a healthy rank."""
        ctx = self._context()
        jr = getattr(ctx, "journal", None) if ctx is not None else None
        if jr is None:
            return 0.0
        pen = 0.0
        try:
            open_rec = 0
            for ev in jr.tail(256):
                e = ev.get("e")
                if e == "recovery_start":
                    open_rec += 1
                elif e == "recovery_done":
                    open_rec = max(0, open_rec - 1)
                elif e == "retire_degraded":
                    pen = max(pen, 0.1)
            if open_rec > 0:
                pen = max(pen, 0.25)
        except Exception:
            return 0.0
        return pen

    def _peer_penalties(self, st: Dict[str, Any],
                        timeout: float) -> Dict[int, float]:
        """Per-peer penalty fold from the comm engine's existing
        state: heartbeat gap inflation + jitter vs the learned
        cadence, silence age vs the death timeout, and comm-delay
        inflation vs the healthy floor."""
        ctx = self._context()
        comm = getattr(ctx, "comm", None) if ctx is not None else None
        ce = getattr(comm, "ce", None) if comm is not None else None
        pens: Dict[int, float] = {}
        if ce is None:
            return pens
        try:
            hb = ce.hb_stats()
        except Exception:
            hb = {}
        for r, s in hb.items():
            if int(s.get("n", 0)) < 3:
                continue
            ewma = float(s.get("ewma_s", 0.0))
            base = self._hb_base.get(r)
            base = ewma if base is None or ewma < base else base
            self._hb_base[r] = base
            pen = 0.0
            if base > 0.0:
                infl = ewma / base - 1.0
                pen += _clamp(0.6 * max(0.0, infl - 0.25), 0.0, 0.5)
                pen += _clamp(0.8 * (float(s.get("jitter_s", 0.0))
                                     / base - 0.25), 0.0, 0.3)
            if timeout > 0.0:
                frac = float(s.get("age_s", 0.0)) / timeout
                pen += _clamp(1.5 * max(0.0, frac - 0.2), 0.0, 0.6)
            pens[r] = pens.get(r, 0.0) + pen
        for r, d in (st.get("peer_comm_delay_s") or {}).items():
            try:
                r, d = int(r), float(d)
            except Exception:
                continue
            if d <= 0.0:
                continue
            base = self._delay_base.get(r)
            base = d if base is None or d < base else base
            self._delay_base[r] = base
            if base > 0.0:
                infl = d / base - 1.0
                pens[r] = pens.get(r, 0.0) + \
                    _clamp(0.1 * max(0.0, infl - 1.0), 0.0, 0.5)
        return pens

    # -- the fold ---------------------------------------------------------

    # lint: hot-path (fabric dispatcher tick + every scrape: PCL-HOT
    # keeps per-fold lock/allocation creep out of this chain)
    def refresh(self, force: bool = False) -> Dict[int, dict]:
        """One rate-limited fold; returns :meth:`snapshot`.  Callers
        are the metrics scrape and the fabric's dispatcher tick —
        never the task hot path."""
        now = time.monotonic()
        with self._lock:  # lint: ignore[PCL-HOT] (THE scrape-side monitor lock: one round-trip per rate-limited fold, not per task)
            if not force and now - self._last_fold < self._interval:
                return self._snapshot_locked(now)
            self._last_fold = now
            self._fold_locked(now)
            return self._snapshot_locked(now)

    # holds-lock: _lock
    def _fold_locked(self, now: float) -> None:
        ctx = self._context()
        rank = getattr(ctx, "rank", 0) if ctx is not None else 0
        comm = getattr(ctx, "comm", None) if ctx is not None else None
        st: Dict[str, Any] = {}
        if comm is not None:
            try:
                st = comm.stats()
            except Exception:
                st = {}
        timeout = float(params.get("comm_peer_timeout_s", 15.0))
        self_pen = (self._pen_stragglers() + self._pen_sojourn_drift()
                    + self._pen_bailouts() + self._pen_backpressure(st)
                    + self._pen_journal())
        scores = {rank: _clamp(1.0 - self_pen)}
        for r, pen in self._peer_penalties(st, timeout).items():
            if r != rank:
                scores[r] = _clamp(1.0 - pen)
        for r, score in scores.items():
            self._observe_locked(r, score, now)
        self.folds += 1

    # holds-lock: _lock
    def _observe_locked(self, r: int, score: float, now: float) -> None:
        rh = self._ranks.get(r)
        if rh is None:
            rh = self._ranks[r] = _RankHealth(r, self._cap)
        rh.score = score
        rh.ewma += self._alpha * (score - rh.ewma)
        rh.series.append((now, round(score, 4)))
        rh.n += 1
        pts = [s for _, s in list(rh.series)[-8:]]
        if len(pts) >= 4:
            half = len(pts) // 2
            rh.trend = round(sum(pts[half:]) / (len(pts) - half)
                             - sum(pts[:half]) / half, 4)
        else:
            rh.trend = 0.0
        new = self._state_for(rh)
        if new != rh.state:
            old, rh.state, rh.since = rh.state, new, now
            self.transitions += 1
            self._journal_transition(r, old, new, rh.ewma)

    def _state_for(self, rh: _RankHealth) -> str:
        e = rh.ewma
        if rh.state == "critical":
            # climb out only past the hysteresis margin
            if e >= self._thr_deg + self._hyst:
                return "ok"
            if e >= self._thr_crit + self._hyst:
                return "degraded"
            return "critical"
        if rh.state == "degraded":
            if e < self._thr_crit:
                return "critical"
            if e >= self._thr_deg + self._hyst:
                return "ok"
            return "degraded"
        if e < self._thr_crit:
            return "critical"
        if e < self._thr_deg:
            return "degraded"
        return "ok"

    def _journal_transition(self, r: int, old: str, new: str,
                            ewma: float) -> None:
        ctx = self._context()
        jr = getattr(ctx, "journal", None) if ctx is not None else None
        if jr is not None:
            jr.emit("health_transition", peer=r, frm=old, to=new,
                    score=round(ewma, 4))
        debug_verbose(2, "health: rank %d %s -> %s (score %.3f)",
                      r, old, new, ewma)

    # -- accessors --------------------------------------------------------

    # holds-lock: _lock
    def _snapshot_locked(self, now: float) -> Dict[int, dict]:
        return {r: {"score": round(rh.score, 4),
                    "ewma": round(rh.ewma, 4),
                    "trend": rh.trend,
                    "state": rh.state,
                    "since_s": round(now - rh.since, 3),
                    "n": rh.n}
                for r, rh in self._ranks.items()}

    def snapshot(self) -> Dict[int, dict]:
        """Current per-rank scoring state (no fold)."""
        with self._lock:
            return self._snapshot_locked(time.monotonic())

    def evidence(self, rank: int, k: int = 8) -> List[List[float]]:
        """The drain decision's evidence: the last ``k`` scored points
        of ``rank`` as ``[age_seconds, score]`` pairs (newest last).
        Journaled verbatim with every ``health_drain``."""
        now = time.monotonic()
        with self._lock:
            rh = self._ranks.get(rank)
            pts = list(rh.series)[-k:] if rh is not None else []
        return [[round(now - t, 3), s] for t, s in pts]

    def series_snapshot(self) -> Dict[int, List[List[float]]]:
        """Every rank's bounded score series (flight-recorder bundles);
        points are ``[age_seconds, score]``, newest last."""
        now = time.monotonic()
        with self._lock:
            return {r: [[round(now - t, 3), s] for t, s in rh.series]
                    for r, rh in self._ranks.items()}

    # lint: hot-path (scrape entry: rides every TAG_METRICS pull)
    def section(self) -> dict:
        """The per-rank wire form riding the TAG_METRICS pull (the
        liveattr section precedent: one extra sample record, zero new
        wire tags)."""
        ctx = self._context()
        now = time.monotonic()
        with self._lock:  # lint: ignore[PCL-HOT] (scrape-side snapshot lock, once per pull)
            return {"v": 1,
                    "rank": getattr(ctx, "rank", 0)
                    if ctx is not None else 0,
                    "scores": {str(r): {"score": round(rh.score, 4),
                                        "ewma": round(rh.ewma, 4),
                                        "trend": rh.trend,
                                        "state": rh.state,
                                        "since_s": round(now - rh.since,
                                                         3),
                                        "n": rh.n}
                               for r, rh in self._ranks.items()},
                    "folds": self.folds,
                    "transitions": self.transitions}

    # lint: hot-path (scrape entry: rides every /metrics exposition)
    def samples(self) -> List[dict]:
        """Prometheus-side additions (ride RuntimeMetrics.samples)."""
        out: List[dict] = []
        now = time.monotonic()
        with self._lock:  # lint: ignore[PCL-HOT] (scrape-side snapshot lock, once per scrape)
            for r, rh in self._ranks.items():
                out.append(gauge_sample("parsec_rank_health", rh.ewma,
                                        {"rank": str(r)}))
                out.append(gauge_sample("parsec_rank_health_trend",
                                        rh.trend, {"rank": str(r)}))
            out.append(counter_sample("parsec_health_transitions_total",
                                      self.transitions))
            out.append(counter_sample("parsec_health_folds_total",
                                      self.folds))
        del now
        return out


def merge_health(sections: Optional[Dict[int, dict]]) -> dict:
    """Fold per-rank ``__health__`` sections into one cluster view.
    Counts (folds / transitions) sum EXACTLY; per-rank scores merge
    PESSIMISTICALLY — the lowest smoothed score any rank observed
    wins, self-view or peer-view alike (a wedged rank's rosy
    self-report must not mask what its peers measure), with the
    observing rank recorded as ``src``.  Ranks whose section is
    absent (a mid-pull death, a disabled plane) are tolerated: they
    simply contribute nothing."""
    ranks: Dict[int, dict] = {}
    folds = 0
    transitions = 0
    for rank in sorted(sections or {}):
        sec = (sections or {}).get(rank) or {}
        folds += int(sec.get("folds", 0) or 0)
        transitions += int(sec.get("transitions", 0) or 0)
        src = int(sec.get("rank", rank))
        for tgt_s, ent in (sec.get("scores") or {}).items():
            try:
                tgt = int(tgt_s)
                ewma = float(ent.get("ewma", 1.0))
            except Exception:
                continue
            cur = ranks.get(tgt)
            if cur is None or ewma < cur["ewma"]:
                ranks[tgt] = {"score": float(ent.get("score", ewma)),
                              "ewma": ewma,
                              "trend": float(ent.get("trend", 0.0)),
                              "state": str(ent.get("state", "ok")),
                              "since_s": float(ent.get("since_s", 0.0)),
                              "n": int(ent.get("n", 0)),
                              "src": src}
    return {"ranks": ranks, "folds": folds, "transitions": transitions}
