"""PINS: performance instrumentation hooks on the task lifecycle.

Rebuild of the reference's PINS framework (reference: parsec/mca/pins/ —
callback chains on task lifecycle events SELECT/EXEC/COMPLETE_EXEC/...
(pins.h:22-50) invoked by PARSEC_PINS macros in scheduling.c; the
``task_profiler`` module feeds the binary tracer).  The runtime already
emits events through ``ExecutionStream.pins`` (core/context.py); modules
here subscribe to them.
"""

from __future__ import annotations

from time import perf_counter as _now
from typing import Any, Dict, Optional

from parsec_tpu.prof.profiling import EV_END, EV_POINT, EV_START, Profile

#: lifecycle events emitted by the runtime (scheduling.py / context.py).
#: ``task_discard`` fires for tasks dropped by pool cancellation; the
#: ``job_*`` events are emitted by the job service (service/service.py)
#: with the Job as payload.
#: ``device_dispatch``/``device_done`` bracket a device task's
#: accelerator-pipeline residency (devices/xla.py, gated on the causal
#: tracer being installed).
PINS_EVENTS = ("select", "exec_begin", "exec_end", "exec_async",
               "complete_exec", "task_discard",
               "device_dispatch", "device_done",
               "job_submit", "job_start", "job_done")


class TaskProfilerPins:
    """Feed task execution intervals into the binary trace
    (reference: mca/pins/task_profiler).

    Hot-path discipline (reference: profiling.c writes one fixed-size
    record with no allocation): the per-event path caches the stream
    buffer per es and the dictionary key per task class, and by default
    records NO Python info payload — info-less events land straight in
    the native C++ packed buffer.  ``with_locals=True`` restores the
    per-event ``{"locals": ...}`` payload (richer traces, Python-path
    cost; the reference's converter-string info analog).
    """

    def __init__(self, profile: Profile, with_locals: bool = False):
        self.profile = profile
        self.with_locals = with_locals
        self._sbs: Dict[int, Any] = {}         # th_id -> StreamBuffer
        self._keys: Dict[str, int] = {}        # class name -> dict key
        self._tagged: list = []                # objects carrying caches
        # hot-path bindings: the raw event-id counter and (per stream,
        # below) the C sink's interval FASTCALL — each skipped Python
        # frame is ~0.1us of the 1us/task tracer budget
        ids = getattr(profile, "_event_ids", None)
        self._next_eid = ids.__next__ if ids is not None \
            else profile.next_event_id

    def install(self, context) -> None:
        # one task_profiler per context: the interval state rides the
        # shared Task.prof slot, so two instances would corrupt each
        # other's streams (the reference's task_profiler is likewise a
        # per-process singleton — PINS modules are MCA-selected once)
        cur = getattr(context, "_task_profiler", None)
        if cur is not None and cur is not self:
            raise RuntimeError(
                "a TaskProfilerPins is already installed on this "
                "context; uninstall it first")
        context._task_profiler = self
        context.pins_register("exec_begin", self._begin)
        context.pins_register("exec_end", self._end)
        context.pins_register("complete_exec", self._complete)

    def uninstall(self, context) -> None:
        if getattr(context, "_task_profiler", None) is self:
            context._task_profiler = None
        context.pins_unregister("exec_begin", self._begin)
        context.pins_unregister("exec_end", self._end)
        context.pins_unregister("complete_exec", self._complete)
        # drop the hot-path caches planted on streams/task classes so an
        # uninstalled profiler (and its Profile's event buffers) does not
        # stay reachable for the life of the context
        for obj, attr in self._tagged:
            if getattr(obj, attr, (None,))[0] is self:
                try:
                    delattr(obj, attr)
                except AttributeError:
                    pass
        self._tagged.clear()

    def _sb(self, es):
        sb = self._sbs.get(es.th_id)
        if sb is None:
            sb = self._sbs[es.th_id] = \
                self.profile.stream(es.th_id, f"worker-{es.th_id}")
        # hot-path cache, owner-tagged so a second profiler instance
        # on the same context cannot reuse the wrong stream; the third
        # slot is the C sink's interval FASTCALL (or None), called
        # directly from _end/_complete — no Python frame.  The tag is
        # (re)planted on EVERY slow-path call, not only on stream
        # creation: _end/_complete re-read es._prof_sb after calling
        # here, and a tag left behind by a previous profiler must not
        # route our END records into its streams
        cs = es.__dict__.get("_prof_sb")
        if cs is None or cs[0] is not self or cs[1] is not sb:
            es._prof_sb = (self, sb, getattr(sb, "_sink_interval", None))
            self._tagged.append((es, "_prof_sb"))
        return sb

    def _key(self, name: str) -> int:
        k = self._keys.get(name)
        if k is None:
            k = self._keys[name] = self.profile.add_event_class(name).key
        return k

    # The per-task state rides the Task.prof slot as
    # [dict key, event id, object id, closed-by-end, taskpool id,
    # begin-timestamp] — no module-level dict/set traffic on the hot
    # path (reference: profiling.c's record path touches only the
    # per-thread buffer; sp-perf.c is the bar).  Info-less intervals
    # DEFER the begin record: _begin only captures a perf_counter()
    # read, and the closing edge writes BOTH records through ONE C
    # crossing (StreamBuffer.interval -> pinsext interval, VERDICT r5
    # #5).  Events carrying an info payload keep the eager two-record
    # path.

    def _begin(self, es, event, task) -> None:
        if not self.profile.enabled:
            return
        tc = task.task_class
        ck = tc.__dict__.get("_prof_key")
        if ck is None or ck[0] is not self:
            k = self._key(tc.name)
            tc._prof_key = (self, k)
            self._tagged.append((tc, "_prof_key"))
        else:
            k = ck[1]
        eid = self._next_eid()
        oid = hash(task.key)
        tpid = task.taskpool.taskpool_id
        if not self.with_locals:
            # the timestamp is the last thing taken: it marks the edge
            task.prof = [k, eid, oid, False, tpid, _now()]
            return
        cs = es.__dict__.get("_prof_sb")
        sb = cs[1] if (cs is not None and cs[0] is self) else self._sb(es)
        task.prof = [k, eid, oid, False, tpid, None]
        sb.trace(k, EV_START, tpid, eid, oid,
                 {"locals": dict(task.locals)})

    def _end(self, es, event, task) -> None:
        p = task.prof
        if p is None or not self.profile.enabled:
            return
        p[3] = True
        cs = es.__dict__.get("_prof_sb")
        if cs is None or cs[0] is not self:
            self._sb(es)
            cs = es._prof_sb
        if p[5] is not None and cs[2] is not None:
            cs[2](p[0], p[4], p[1], p[2], p[5], EV_START, EV_END)
        elif p[5] is not None:
            cs[1].interval(p[0], p[4], p[1], p[2], p[5])
        else:
            cs[1].trace(p[0], EV_END, p[4], p[1], p[2])

    def _complete(self, es, event, task) -> None:
        # device (ASYNC) tasks never ran exec_end on a worker stream:
        # close their interval at completion
        p = task.prof
        if p is None:
            return
        task.prof = None
        if p[3] or not self.profile.enabled:    # closed by _end already
            return
        cs = es.__dict__.get("_prof_sb")
        if cs is None or cs[0] is not self:
            self._sb(es)
            cs = es._prof_sb
        if p[5] is not None and cs[2] is not None:
            cs[2](p[0], p[4], p[1], p[2], p[5], EV_START, EV_END)
        elif p[5] is not None:
            cs[1].interval(p[0], p[4], p[1], p[2], p[5])
        else:
            cs[1].trace(p[0], EV_END, p[4], p[1], p[2])


def install_task_profiler(context, profile: Profile,
                          with_locals: bool = False) -> TaskProfilerPins:
    mod = TaskProfilerPins(profile, with_locals=with_locals)
    mod.install(context)
    return mod


class StealCounterPins:
    """Per-stream select counters (reference: mca/pins/print_steals)."""

    def __init__(self):
        self.selects: Dict[int, int] = {}

    def install(self, context) -> None:
        context.pins_register("select", self._select)

    def uninstall(self, context) -> None:
        context.pins_unregister("select", self._select)

    def _select(self, es, event, task) -> None:
        self.selects[es.th_id] = self.selects.get(es.th_id, 0) + 1

    def display(self) -> str:
        total = sum(self.selects.values())
        per = " ".join(f"es{t}={n}" for t, n in sorted(self.selects.items()))
        return f"selects total={total} {per}"


class GaugesPins:
    """Bridge to the live gauges (reference: the alperf/papi_sde-style
    modules exporting runtime counters)."""

    def __init__(self):
        from parsec_tpu.prof.gauges import Gauges
        self.gauges = Gauges()

    def install(self, context) -> None:
        self.gauges.install(context)

    def uninstall(self, context) -> None:
        self.gauges.uninstall(context)

    def display(self) -> str:
        return str(self.gauges.snapshot())


class IteratorsCheckerPins:
    """Successor-iteration validator (reference:
    mca/pins/iterators_checker — re-derives a completed task's successor
    set and cross-checks it against the dependencies the engine actually
    delivered; valuable precisely because this runtime's dep engine is
    hand-written per front-end).  Per completed PTG task it re-walks the
    flow expressions (iterate_successors) and compares with the
    ``deliver_dep`` calls observed through the PINS hook: a lost or
    extra delivery is reported as a context error.  Dynamic (DTD) pools
    resolve successors from their runtime graph, not flow expressions,
    and are skipped."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        #: id(task) -> set of (succ class name, succ key, flow name)
        self._delivered: Dict[int, set] = {}
        self.checked = 0
        self.flagged = 0

    def install(self, context) -> None:
        context.pins_register("deliver_dep", self._deliver)
        context.pins_register("complete_exec", self._complete)

    def uninstall(self, context) -> None:
        context.pins_unregister("deliver_dep", self._deliver)
        context.pins_unregister("complete_exec", self._complete)

    def _deliver(self, es, event, payload) -> None:
        task, succ_tc, succ_locals, dflow = payload
        with self._lock:
            self._delivered.setdefault(id(task), set()).add(
                (succ_tc.name, succ_tc.make_key(succ_locals), dflow))

    def _expected(self, task) -> set:
        from parsec_tpu.core.task import ToTask
        tp = task.taskpool
        myrank = tp.context.rank if tp.context else 0
        want = set()
        for flow in task.task_class.flows:
            for dep in flow.active_outputs(task.locals):
                end = dep.end
                if not isinstance(end, ToTask):
                    continue
                succ_tc = tp.task_classes[end.task_class]
                for succ_locals in end.instances(task.locals):
                    # dep instances carry free params only; fill derived
                    # locals before keying/ranking (mirrors release_deps,
                    # else every derived-local successor class silently
                    # escapes validation via the except below)
                    succ_locals = succ_tc.complete_locals(succ_locals)
                    if succ_tc.rank_of(succ_locals) != myrank:
                        continue
                    want.add((succ_tc.name, succ_tc.make_key(succ_locals),
                              end.flow))
        return want

    def _complete(self, es, event, task) -> None:
        if getattr(task.taskpool, "dynamic_release", None) is not None:
            return          # DTD: successors come from the runtime graph
        with self._lock:
            got = self._delivered.pop(id(task), set())
        try:
            want = self._expected(task)
        except Exception:
            return          # un-evaluable expressions: nothing to check
        self.checked += 1
        if got != want:
            self.flagged += 1
            missing = want - got
            extra = got - want
            es.context.record_error(AssertionError(
                f"iterators_checker: {task} successor mismatch — "
                f"missing deliveries: {sorted(missing)}; "
                f"unexpected deliveries: {sorted(extra)}"), task)

    def display(self) -> str:
        return f"iterators_checker checked={self.checked} " \
               f"flagged={self.flagged}"


#: name -> zero-arg constructor; the MCA-selected modules of ``--mca
#: pins a,b`` (reference: the pins framework's module list, pins_init.c)
_MODULES = {
    "print_steals": StealCounterPins,
    "alperf": GaugesPins,
    "iterators_checker": IteratorsCheckerPins,
}


def install_selected(context) -> list:
    """Install the PINS modules named by ``--mca pins`` (comma list) on
    a context; returns the module instances (reference: pins_init
    iterating the selected module list).  Unknown names warn rather than
    fail — a missing instrumentation module must not kill the run."""
    from parsec_tpu.utils.mca import params
    from parsec_tpu.utils.output import warning
    params.register("pins", "",
                    "comma-separated PINS instrumentation modules to "
                    "install at context init "
                    f"(available: {', '.join(sorted(_MODULES))})")
    spec = str(params.get("pins", "") or "").strip()
    mods = []
    if not spec:
        return mods
    for name in spec.split(","):
        name = name.strip()
        if not name:
            continue
        ctor = _MODULES.get(name)
        if ctor is None:
            warning("unknown PINS module %r (available: %s)", name,
                    ", ".join(sorted(_MODULES)))
            continue
        mod = ctor()
        mod.install(context)
        mods.append(mod)
    return mods
