"""PINS: performance instrumentation hooks on the task lifecycle.

Rebuild of the reference's PINS framework (reference: parsec/mca/pins/ —
callback chains on task lifecycle events SELECT/EXEC/COMPLETE_EXEC/...
(pins.h:22-50) invoked by PARSEC_PINS macros in scheduling.c; the
``task_profiler`` module feeds the binary tracer).  The runtime already
emits events through ``ExecutionStream.pins`` (core/context.py); modules
here subscribe to them.
"""

from __future__ import annotations

from typing import Dict, Optional

from parsec_tpu.prof.profiling import EV_POINT, Profile

#: lifecycle events emitted by the runtime (scheduling.py / context.py)
PINS_EVENTS = ("select", "exec_begin", "exec_end", "exec_async",
               "complete_exec")


class TaskProfilerPins:
    """Feed task execution intervals into the binary trace
    (reference: mca/pins/task_profiler)."""

    def __init__(self, profile: Profile):
        self.profile = profile
        self._event_ids: Dict[int, int] = {}   # task seq -> trace event id
        self._closed: set = set()              # eids closed by exec_end

    def install(self, context) -> None:
        context.pins_register("exec_begin", self._begin)
        context.pins_register("exec_end", self._end)
        context.pins_register("complete_exec", self._complete)

    def uninstall(self, context) -> None:
        context.pins_unregister("exec_begin", self._begin)
        context.pins_unregister("exec_end", self._end)
        context.pins_unregister("complete_exec", self._complete)

    def _sb(self, es):
        return self.profile.stream(es.th_id, f"worker-{es.th_id}")

    def _begin(self, es, event, task) -> None:
        eid = self.profile.next_event_id()
        self._event_ids[task.seq] = eid
        self.profile.trace_interval_start(
            self._sb(es), task.task_class.name, task.taskpool.taskpool_id,
            eid, object_id=hash(task.key),
            info={"locals": dict(task.locals)})

    def _end(self, es, event, task) -> None:
        eid = self._event_ids.get(task.seq, 0)
        self._closed.add(eid)
        self.profile.trace_interval_end(
            self._sb(es), task.task_class.name, task.taskpool.taskpool_id,
            eid, object_id=hash(task.key))

    def _complete(self, es, event, task) -> None:
        # device (ASYNC) tasks never ran exec_end on a worker stream:
        # close their interval at completion (closed-set membership, not
        # a buffer scan — END events may live in the native buffer)
        eid = self._event_ids.pop(task.seq, None)
        if eid is None:
            return
        if eid in self._closed:             # already closed by _end
            self._closed.discard(eid)
            return
        self.profile.trace_interval_end(
            self._sb(es), task.task_class.name, task.taskpool.taskpool_id,
            eid, object_id=hash(task.key))


def install_task_profiler(context, profile: Profile) -> TaskProfilerPins:
    mod = TaskProfilerPins(profile)
    mod.install(context)
    return mod


class StealCounterPins:
    """Per-stream select counters (reference: mca/pins/print_steals)."""

    def __init__(self):
        self.selects: Dict[int, int] = {}

    def install(self, context) -> None:
        context.pins_register("select", self._select)

    def uninstall(self, context) -> None:
        context.pins_unregister("select", self._select)

    def _select(self, es, event, task) -> None:
        self.selects[es.th_id] = self.selects.get(es.th_id, 0) + 1

    def display(self) -> str:
        total = sum(self.selects.values())
        per = " ".join(f"es{t}={n}" for t, n in sorted(self.selects.items()))
        return f"selects total={total} {per}"


class GaugesPins:
    """Bridge to the live gauges (reference: the alperf/papi_sde-style
    modules exporting runtime counters)."""

    def __init__(self):
        from parsec_tpu.prof.gauges import Gauges
        self.gauges = Gauges()

    def install(self, context) -> None:
        self.gauges.install(context)

    def uninstall(self, context) -> None:
        self.gauges.uninstall(context)

    def display(self) -> str:
        return str(self.gauges.snapshot())


#: name -> zero-arg constructor; the MCA-selected modules of ``--mca
#: pins a,b`` (reference: the pins framework's module list, pins_init.c)
_MODULES = {
    "print_steals": StealCounterPins,
    "alperf": GaugesPins,
}


def install_selected(context) -> list:
    """Install the PINS modules named by ``--mca pins`` (comma list) on
    a context; returns the module instances (reference: pins_init
    iterating the selected module list).  Unknown names warn rather than
    fail — a missing instrumentation module must not kill the run."""
    from parsec_tpu.utils.mca import params
    from parsec_tpu.utils.output import warning
    params.register("pins", "",
                    "comma-separated PINS instrumentation modules to "
                    "install at context init "
                    f"(available: {', '.join(sorted(_MODULES))})")
    spec = str(params.get("pins", "") or "").strip()
    mods = []
    if not spec:
        return mods
    for name in spec.split(","):
        name = name.strip()
        if not name:
            continue
        ctor = _MODULES.get(name)
        if ctor is None:
            warning("unknown PINS module %r (available: %s)", name,
                    ", ".join(sorted(_MODULES)))
            continue
        mod = ctor()
        mod.install(context)
        mods.append(mod)
    return mods
