"""Live gauge aggregator: the aggregator_visu counterpart.

Rebuild of the reference's live-visualization pipeline (reference:
tools/aggregator_visu/aggregator.py + *_thread.py — per-rank PAPI-SDE
gauges stream to an aggregator process holding a keyed time-series
store; GUIs subscribe and plot).  TPU-first reshape: one lightweight
TCP aggregator thread holds the latest snapshot and a bounded history
per (rank, gauge); ranks publish via ``GaugePublisher`` (a periodic
thread reading prof/gauges.py Gauges.snapshot()); consumers poll
``Aggregator.table()`` or subscribe a callback — the terminal viewer
``tools/live_view.py`` renders it live (the aggregator GUI's role
without a display server).

Wire format: one JSON object per line — {"rank": r, "t": seconds,
"gauges": {...}} — so anything (curl, netcat, a notebook) can publish
or scrape.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple


class Aggregator:
    """Keyed latest-value + bounded-history store behind a TCP listener
    (reference: aggregator_database_thread.py's store)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 history: int = 512):
        self._lock = threading.Lock()
        self._latest: Dict[int, Dict[str, float]] = {}
        self._seen_at: Dict[int, float] = {}
        self._hist: Dict[Tuple[int, str], deque] = {}
        self._history = history
        self._subs: List[Callable[[int, Dict[str, float]], None]] = []
        self._stop = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="gauge-aggregator",
                                        daemon=True)
        self._thread.start()

    # -- ingest ------------------------------------------------------------
    def _accept_loop(self) -> None:
        # close() flags _stop and joins this thread BEFORE closing the
        # socket, so the fd stays valid for the life of the loop; the
        # guard covers the join-timeout fallback where close() proceeds.
        try:
            self._srv.settimeout(0.2)
        except OSError:
            return
        while not self._stop:
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # daemon handlers self-terminate on _stop / peer close
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True).start()

    def _recv_loop(self, conn: socket.socket) -> None:
        buf = b""
        conn.settimeout(1.0)
        while not self._stop:
            try:
                chunk = conn.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            while b"\n" in buf:
                line, _, buf = buf.partition(b"\n")
                try:
                    msg = json.loads(line)
                    self.ingest(int(msg["rank"]), msg["gauges"],
                                float(msg.get("t", time.time())))
                except (ValueError, KeyError, TypeError):
                    continue   # malformed line: drop, keep the stream

    def ingest(self, rank: int, gauges: Dict[str, float],
               t: Optional[float] = None) -> None:
        t = time.time() if t is None else t
        # The wire accepts arbitrary JSON values; keep only numerics so
        # render_table/history never see a string/null from a publisher.
        clean = {k: float(v) for k, v in gauges.items()
                 if isinstance(v, (int, float))}
        with self._lock:
            self._latest[rank] = dict(clean)
            self._seen_at[rank] = t
            for k, v in clean.items():
                h = self._hist.get((rank, k))
                if h is None:
                    h = self._hist[(rank, k)] = deque(maxlen=self._history)
                h.append((t, v))
            subs = list(self._subs)
        for cb in subs:
            cb(rank, clean)

    # -- consume -----------------------------------------------------------
    def subscribe(self, cb: Callable[[int, Dict[str, float]], None]):
        with self._lock:
            self._subs.append(cb)

    def table(self) -> Dict[int, Dict[str, float]]:
        """Latest snapshot per rank (plus staleness in seconds)."""
        now = time.time()
        with self._lock:
            out = {}
            for r, g in sorted(self._latest.items()):
                row = dict(g)
                row["_age_s"] = round(now - self._seen_at.get(r, now), 2)
                out[r] = row
            return out

    def history(self, rank: int, gauge: str) -> List[Tuple[float, float]]:
        with self._lock:
            return list(self._hist.get((rank, gauge), ()))

    def totals(self) -> Dict[str, float]:
        """Cross-rank sums — the math-thread's aggregate view
        (reference: aggregator_math_thread.py)."""
        with self._lock:
            tot: Dict[str, float] = {}
            for g in self._latest.values():
                for k, v in g.items():
                    if isinstance(v, (int, float)):
                        tot[k] = tot.get(k, 0.0) + v
            return tot

    def close(self) -> None:
        self._stop = True
        self._thread.join(timeout=2)
        try:
            self._srv.close()
        except OSError:
            pass


class GaugePublisher:
    """Periodically publish a rank's Gauges snapshot to an aggregator
    (reference: the app-side PAPI-SDE stream the demo servers emit)."""

    def __init__(self, gauges: Any, rank: int, host: str, port: int,
                 interval: float = 0.25):
        self.gauges = gauges
        self.rank = rank
        self.interval = interval
        self._addr = (host, port)
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"gauge-pub-{rank}",
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.publish_once()
            self._stop.wait(self.interval)

    def publish_once(self) -> bool:
        try:
            if self._sock is None:
                self._sock = socket.create_connection(self._addr,
                                                      timeout=1.0)
            msg = {"rank": self.rank, "t": time.time(),
                   "gauges": self.gauges.snapshot()}
            self._sock.sendall((json.dumps(msg) + "\n").encode())
            return True
        except OSError:
            try:
                if self._sock is not None:
                    self._sock.close()
            finally:
                self._sock = None
            return False

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self.publish_once()          # final flush
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass


def render_table(table: Dict[int, Dict[str, float]],
                 totals: Optional[Dict[str, float]] = None) -> str:
    """Fixed-width text rendering of the per-rank gauge table (the
    basic_gui.py role, terminal-friendly)."""
    if not table:
        return "(no ranks reporting)"
    cols = sorted({k for g in table.values() for k in g if k != "_age_s"})
    widths = {c: max(len(c), 12) for c in cols}
    lines = ["rank  " + "  ".join(c.rjust(widths[c]) for c in cols)
             + "   age"]
    for r, g in table.items():
        lines.append(f"{r:4d}  " + "  ".join(
            f"{g.get(c, 0):{widths[c]}.0f}" for c in cols)
            + f"  {g.get('_age_s', 0):4.1f}s")
    if totals:
        lines.append(" sum  " + "  ".join(
            f"{totals.get(c, 0):{widths[c]}.0f}" for c in cols) + "      ")
    return "\n".join(lines)
