"""Trace reader: .ptt files -> pandas DataFrame.

Rebuild of the reference's trace tooling (reference: tools/profiling/
dbpreader.c + python/pbt2ptt.pyx + parsec_trace_tables.py — binary trace
to pandas tables with one row per event, interval events paired into
begin/end rows).  ``read_trace`` returns (meta, events_df) where the
DataFrame has columns: stream, key, name, flags, taskpool_id, event_id,
object_id, ts, info; ``intervals`` pairs START/END rows into one row per
executed task with a duration.

The reader is FORWARD-TOLERANT: event classes it has never seen (new
tracer modules add them every round), dictionary entries carrying extra
fields, and point events interleaved with intervals all pass through —
an analysis tool built on an older dictionary must degrade to "unknown
class", not crash (the r6 causal tracer was the forcing case).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Dict, Tuple

from parsec_tpu.prof.profiling import EV_END, EV_START, MAGIC, _EV


def read_trace(path: str):
    import pandas as pd
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:8] != MAGIC:
        raise ValueError(f"{path}: not a parsec_tpu trace")
    off = 8
    (mlen,) = struct.unpack_from("!Q", raw, off)
    off += 8
    meta = pickle.loads(raw[off:off + mlen])
    off += mlen
    # dictionary entries are (key, name, attrs) today; tolerate future
    # fields riding along (and, defensively, attr-less pairs)
    key_names = {}
    for entry in meta.get("dictionary", ()):
        if len(entry) >= 2:
            key_names[entry[0]] = entry[1]
    rows = []
    for stream_id, name, nev in meta["streams"]:
        events = []
        for _ in range(nev):
            events.append(_EV.unpack_from(raw, off))
            off += _EV.size
        (ilen,) = struct.unpack_from("!Q", raw, off)
        off += 8
        infos = pickle.loads(raw[off:off + ilen])
        off += ilen
        for i, (key, flags, tp, eid, oid, ts) in enumerate(events):
            rows.append({
                "stream": stream_id, "key": key,
                "name": key_names.get(key, f"key{key}"),
                "flags": flags, "taskpool_id": tp, "event_id": eid,
                "object_id": oid, "ts": ts, "info": infos.get(i),
            })
    return meta, pd.DataFrame(rows)


def intervals(events_df):
    """Pair START/END events into one row per interval with duration.

    Pairing is by event id — and by ``rank`` too when the frame carries
    one (merged multi-rank traces: each rank's profile numbers its
    events independently, so cross-rank id collisions must not pair)."""
    import pandas as pd
    keys = ["event_id"]
    if "rank" in events_df.columns:
        keys = ["rank", "event_id"]
    starts = events_df[(events_df["flags"] & EV_START) != 0]
    ends = events_df[(events_df["flags"] & EV_END) != 0]
    merged = starts.merge(
        ends[keys + ["ts"]], on=keys,
        suffixes=("_begin", "_end"))
    merged["duration"] = merged["ts_end"] - merged["ts_begin"]
    return merged

