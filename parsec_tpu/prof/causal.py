"""Causal tracer: cross-layer span + flow-edge events for one rank.

The per-rank half of the causal trace pipeline (reference: the PINS
task_profiler records *execution* intervals; the comm engine's OTF2
backend records send/recv — here one module owns every causal event so a
merged multi-rank trace decomposes each task's latency into queue-wait /
exec / device / comm segments and carries the flow edges the critical
path needs, prof/critpath.py).

Event classes written into the installed :class:`Profile`:

``queue_wait``
    interval on the selecting worker's stream, ``ready_at`` (stamped by
    core/scheduling.schedule when a tracer is installed) -> select;
    object_id = ``hash(task.key)`` — the same oid the task_profiler's
    exec interval carries, so the two join per task.
``dev:<class>``
    interval on the device stream: dispatch (the wave entered the
    accelerator pipeline) -> outputs materialized (devices/xla.py
    ``device_dispatch``/``device_done`` PINS events).
``dep_edge``
    point per LOCAL dependency delivery (the ``deliver_dep`` PINS
    event): object_id = producer oid, info ``{"dst": successor oid}`` —
    the intra-rank DAG edges of the merged causal graph.
``comm_send`` / ``comm_recv``
    point per traced wire frame (comm/remote_dep.py): info carries the
    ``(src_rank, event_seq)`` correlation id, the tag, byte count, and
    — on the recv side — the sender's clock stamp; a matched pair is
    one cross-rank flow edge (Perfetto flow arrows, critpath comm
    segments).
``dep_deliver``
    point per REMOTE delivery on the receiving rank: object_id =
    successor oid, info ``{"corr": ...}`` — binds the flow edge to the
    consumer task.
``dtd_lane``
    point per DTD lane/surrogate operation (dsl/dtd/insert.py): info
    ``{"op", "tile", "lane", "ver", "val"}`` — makes region-lane
    ordering races (ROADMAP: the DTD stale-read flake) diagnosable from
    one merged timeline instead of rerun roulette.

``uninstall`` (or :meth:`finalize`) records the rank and the comm
engine's per-peer clock table (offset/rtt/drift, engine.py TAG_CLOCK
ping exchange) into the profile header; prof/critpath.py and
tools/trace2chrome.py --merge align the per-rank timelines with it.
"""

from __future__ import annotations

import itertools
import json
import threading
from time import perf_counter as _now
from typing import Any, Dict, Optional, Tuple

from parsec_tpu.prof.profiling import EV_POINT, Profile

#: stream id of the comm/causal point events (workers are 0..n, device
#: streams 900+; 800 keeps the lanes apart in any viewer)
COMM_STREAM = 800

#: event-class names with non-task semantics — readers exclude them
#: from "task execution" interval sets
SPECIAL_CLASSES = ("queue_wait", "dep_edge", "comm_send", "comm_recv",
                   "dep_deliver", "dtd_lane")


class CausalTracer:
    """One per context; pair with a TaskProfilerPins on the SAME
    profile so exec intervals and causal spans share a timeline."""

    def __init__(self, profile: Profile, rank: int = 0):
        self.profile = profile
        self.rank = rank
        self._keys: Dict[str, int] = {}
        self._sbs: Dict[int, Any] = {}
        self._comm_sb = profile.stream(COMM_STREAM, "comm")
        #: id(task) -> (t_dispatch, oid, class name, taskpool id)
        self._disp: Dict[int, Tuple] = {}
        self._dlock = threading.Lock()
        self._corr = itertools.count(1)

    # -- lifecycle -------------------------------------------------------
    def install(self, context) -> "CausalTracer":
        self.rank = context.rank
        context._causal_tracer = self
        context._recompute_ready_stamp()
        context.pins_register("select", self._select)
        context.pins_register("deliver_dep", self._deliver_dep)
        context.pins_register("device_dispatch", self._dev_dispatch)
        context.pins_register("device_done", self._dev_done)
        if context.comm is not None:
            context.comm.tracer = self
        return self

    def uninstall(self, context) -> None:
        if getattr(context, "_causal_tracer", None) is self:
            context._causal_tracer = None
            context._recompute_ready_stamp()
        context.pins_unregister("select", self._select)
        context.pins_unregister("deliver_dep", self._deliver_dep)
        context.pins_unregister("device_dispatch", self._dev_dispatch)
        context.pins_unregister("device_done", self._dev_done)
        if context.comm is not None and \
                getattr(context.comm, "tracer", None) is self:
            context.comm.tracer = None
        self.finalize(context)

    def finalize(self, context) -> None:
        """Record rank + clock-alignment table into the trace header
        (what the cross-rank merge aligns timestamps with)."""
        self.profile.add_information("rank", str(context.rank))
        self.profile.add_information("nranks", str(context.nranks))
        ce = getattr(context.comm, "ce", None) \
            if context.comm is not None else None
        table = ce.clock_table() if ce is not None else {}
        if table:
            self.profile.add_information(
                "clock_offsets", json.dumps(
                    {str(r): st["offset"] for r, st in table.items()}))
            self.profile.add_information(
                "clock_rtt", json.dumps(
                    {str(r): st["rtt"] for r, st in table.items()}))
            self.profile.add_information(
                "clock_drift", json.dumps(
                    {str(r): st["drift"] for r, st in table.items()}))

    # -- internals -------------------------------------------------------
    def _key(self, name: str) -> int:
        k = self._keys.get(name)
        if k is None:
            k = self._keys[name] = self.profile.add_event_class(name).key
        return k

    def _sb(self, th_id: int, name: str):
        sb = self._sbs.get(th_id)
        if sb is None:
            sb = self._sbs[th_id] = self.profile.stream(th_id, name)
        return sb

    # -- PINS handlers ---------------------------------------------------
    def _select(self, es, event, task) -> None:
        t0 = task.ready_at
        if t0 is None or not self.profile.enabled:
            return
        task.ready_at = None
        sb = self._sb(es.th_id, f"worker-{es.th_id}")
        sb.interval(self._key("queue_wait"), task.taskpool.taskpool_id,
                    self.profile.next_event_id(), hash(task.key), t0)

    def _deliver_dep(self, es, event, payload) -> None:
        if not self.profile.enabled:
            return
        task, succ_tc, succ_locals, _dflow = payload
        try:
            dst = hash(succ_tc.make_key(succ_locals))
        except Exception:
            return     # un-keyable successor: no edge to record
        sb = self._sb(es.th_id, f"worker-{es.th_id}")
        sb.trace(self._key("dep_edge"), EV_POINT,
                 task.taskpool.taskpool_id, self.profile.next_event_id(),
                 hash(task.key), {"dst": dst})

    def _dev_dispatch(self, es, event, task) -> None:
        with self._dlock:
            self._disp[id(task)] = (_now(), hash(task.key),
                                    task.task_class.name,
                                    task.taskpool.taskpool_id)

    def _dev_done(self, es, event, task) -> None:
        with self._dlock:
            ent = self._disp.pop(id(task), None)
        if ent is None or not self.profile.enabled:
            return
        t0, oid, name, tpid = ent
        sb = self._sb(es.th_id, f"device-{es.th_id}")
        sb.interval(self._key(f"dev:{name}"), tpid,
                    self.profile.next_event_id(), oid, t0)

    # -- comm-layer API (called by comm/remote_dep.py) -------------------
    def next_corr(self) -> Tuple[int, int]:
        """A fresh (src_rank, event_seq) correlation id for one wire
        frame; the same id rides inside the frame and in both the
        sender's comm_send and the receiver's comm_recv events."""
        return (self.rank, next(self._corr))

    def comm_send(self, tag: int, dst: int, corr: Tuple[int, int],
                  oid: Optional[int], nbytes: int,
                  sent_at: float, tpid: int = 0,
                  src_rank: Optional[int] = None) -> None:
        if not self.profile.enabled:
            return
        # taskpool id rides the record: task identity is (pool, key
        # hash) — two pools' same-named tasks must not collide in the
        # merged DAG (the bench's warmup pool was the forcing case).
        # src_rank is the PRODUCER's rank (the activation's root): a
        # tree-forwarded frame is sent by an intermediate rank but its
        # oid belongs to the producer's trace — the DAG edge must point
        # there, not at the forwarder
        info = {"corr": corr, "tag": tag, "dst": dst, "nbytes": nbytes}
        if src_rank is not None and src_rank != self.rank:
            info["src_rank"] = src_rank
        self._comm_sb.trace(
            self._key("comm_send"), EV_POINT, tpid,
            self.profile.next_event_id(), oid or 0, info,
            timestamp=sent_at)

    def comm_recv(self, tag: int, src: int, corr, sent_at,
                  nbytes: int) -> None:
        if not self.profile.enabled:
            return
        self._comm_sb.trace(
            self._key("comm_recv"), EV_POINT, 0,
            self.profile.next_event_id(), 0,
            {"corr": tuple(corr), "tag": tag, "src": src,
             "sent_at": sent_at, "nbytes": nbytes})

    def dep_deliver(self, corr, oid: int, tpid: int = 0) -> None:
        if not self.profile.enabled:
            return
        self._comm_sb.trace(
            self._key("dep_deliver"), EV_POINT, tpid,
            self.profile.next_event_id(), oid,
            {"corr": tuple(corr) if corr is not None else None})

    # -- DTD lane events (called by dsl/dtd/insert.py) -------------------
    def dtd_event(self, op: str, tile, lane, ver: int,
                  val: Optional[float] = None) -> None:
        if not self.profile.enabled:
            return
        info = {"op": op, "tile": tile, "lane": lane, "ver": ver}
        if val is not None:
            info["val"] = val
        self._comm_sb.trace(self._key("dtd_lane"), EV_POINT, 0,
                            self.profile.next_event_id(), 0, info)


def install_causal_tracer(context, profile: Profile) -> CausalTracer:
    return CausalTracer(profile, rank=context.rank).install(context)
