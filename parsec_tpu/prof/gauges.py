"""Live runtime gauges: the PAPI-SDE counterpart.

Rebuild of the reference's software-defined-event exports (reference:
parsec/papi_sde.{c,h} — live gauges MEM_ALLOC/MEM_USED/TASKS_ENABLED/
TASKS_RETIRED/SCHEDULER_PENDING_TASKS readable by external consumers
while the runtime runs).  Counters update through PINS events plus
polling hooks; ``snapshot()`` is the external read.
"""

from __future__ import annotations

import threading
from typing import Dict


class Gauges:
    GAUGE_NAMES = ("tasks_enabled", "tasks_retired", "tasks_discarded",
                   "pending_tasks",
                   "device_bytes_in", "device_bytes_out",
                   "device_tasks", "device_evictions",
                   "comm_frames_sent", "comm_frames_recv",
                   "comm_bytes_sent", "comm_bytes_recv",
                   "comm_act_eager", "comm_act_rdv")

    def __init__(self):
        self._lock = threading.Lock()
        self.tasks_enabled = 0     # became ready (scheduled)
        self.tasks_retired = 0     # completed
        self.tasks_discarded = 0   # dropped by pool cancellation
        self.context = None

    def install(self, context) -> None:
        self.context = context
        context.pins_register("select", self._select)
        context.pins_register("complete_exec", self._complete)
        context.pins_register("task_discard", self._discard)

    def uninstall(self, context) -> None:
        context.pins_unregister("select", self._select)
        context.pins_unregister("complete_exec", self._complete)
        context.pins_unregister("task_discard", self._discard)
        self.context = None

    def _select(self, es, event, task) -> None:
        with self._lock:
            self.tasks_enabled += 1

    def _complete(self, es, event, task) -> None:
        with self._lock:
            self.tasks_retired += 1

    def _discard(self, es, event, task) -> None:
        with self._lock:
            self.tasks_discarded += 1

    def snapshot(self) -> Dict[str, float]:
        snap = {
            "tasks_enabled": self.tasks_enabled,
            "tasks_retired": self.tasks_retired,
            "tasks_discarded": self.tasks_discarded,
            "pending_tasks": max(0, self.tasks_enabled - self.tasks_retired
                                 - self.tasks_discarded),
            "device_bytes_in": 0,
            "device_bytes_out": 0,
            "device_tasks": 0,
            "device_evictions": 0,
        }
        ctx = self.context
        for name in ("comm_frames_sent", "comm_frames_recv",
                     "comm_bytes_sent", "comm_bytes_recv",
                     "comm_act_eager", "comm_act_rdv"):
            snap[name] = 0
        if ctx is not None:
            for d in ctx.device_registry.devices[1:]:
                snap["device_bytes_in"] += d.stats.bytes_in
                snap["device_bytes_out"] += d.stats.bytes_out
                snap["device_tasks"] += d.stats.executed_tasks
                snap["device_evictions"] += d.stats.evictions
            comm = getattr(ctx, "comm", None)
            if comm is not None and hasattr(comm, "stats"):
                cs = comm.stats()
                snap["comm_frames_sent"] = cs.get("frames_sent", 0)
                snap["comm_frames_recv"] = cs.get("frames_recv", 0)
                snap["comm_bytes_sent"] = cs.get("bytes_sent", 0)
                snap["comm_bytes_recv"] = cs.get("bytes_recv", 0)
                snap["comm_act_eager"] = cs.get("act_eager", 0)
                snap["comm_act_rdv"] = cs.get("act_rdv", 0)
        return snap


def install_gauges(context) -> Gauges:
    g = Gauges()
    g.install(context)
    return g


class JobGauges:
    """Per-job live gauges for the resident job service
    (service/service.py): aggregate job counts plus per-job task
    counters keyed ``job<N>_*`` so the existing aggregator path
    (prof/aggregator.py GaugePublisher -> Aggregator) publishes them
    unchanged — any ``snapshot()``-bearing object can ride a publisher.

    Task attribution uses the ``job_id`` tag the service plants on each
    job's taskpool(s); tasks of plain batch pools (job_id None) are
    ignored.  Per-job keys are bounded: only the ``max_jobs`` most
    recent jobs keep per-job counters in the snapshot (aggregate counts
    are exact regardless).
    """

    def __init__(self, service, max_jobs: int = 32):
        self._lock = threading.Lock()
        self._service = service
        self._max_jobs = max_jobs
        #: job_id -> [enabled, retired, discarded]
        self._tasks: Dict[int, list] = {}
        self.context = None

    def install(self, context) -> None:
        self.context = context
        context.pins_register("select", self._select)
        context.pins_register("complete_exec", self._complete)
        context.pins_register("task_discard", self._discard)

    def uninstall(self, context) -> None:
        context.pins_unregister("select", self._select)
        context.pins_unregister("complete_exec", self._complete)
        context.pins_unregister("task_discard", self._discard)
        self.context = None

    def _bump(self, task, idx: int) -> None:
        jid = getattr(task.taskpool, "job_id", None)
        if jid is None:
            return
        with self._lock:
            row = self._tasks.get(jid)
            if row is None:
                row = self._tasks[jid] = [0, 0, 0]
                while len(self._tasks) > self._max_jobs:
                    self._tasks.pop(next(iter(self._tasks)))
            row[idx] += 1

    def _select(self, es, event, task) -> None:
        self._bump(task, 0)

    def _complete(self, es, event, task) -> None:
        self._bump(task, 1)

    def _discard(self, es, event, task) -> None:
        self._bump(task, 2)

    def job_task_counts(self, job_id: int) -> Dict[str, int]:
        with self._lock:
            row = self._tasks.get(job_id, (0, 0, 0))
            return {"tasks_enabled": row[0], "tasks_retired": row[1],
                    "tasks_discarded": row[2]}

    def job_task_rows(self):
        """Bounded (job_id, [enabled, retired, discarded]) rows — the
        metrics registry's per-job family rides this window, so its
        label cardinality is capped by max_jobs exactly like the
        gauge keys."""
        with self._lock:
            return [(jid, list(row)) for jid, row in self._tasks.items()]

    def snapshot(self) -> Dict[str, float]:
        import time
        counts: Dict[str, int] = {}
        snap: Dict[str, float] = {}
        now = time.time()
        jobs = list(self._service.jobs())
        for job in jobs:
            st = job.status().name.lower()
            counts[st] = counts.get(st, 0) + 1
        snap["jobs_submitted"] = len(jobs)
        for st in ("pending", "running", "done", "failed", "cancelled",
                   "timeout"):
            snap[f"jobs_{st}"] = counts.get(st, 0)
        with self._lock:
            rows = dict(self._tasks)
        for job in jobs[-self._max_jobs:]:
            jid = job.job_id
            row = rows.get(jid, (0, 0, 0))
            snap[f"job{jid}_tasks_enabled"] = row[0]
            snap[f"job{jid}_tasks_retired"] = row[1]
            snap[f"job{jid}_tasks_discarded"] = row[2]
            snap[f"job{jid}_priority"] = job.priority
            end = job.finished_at if job.finished_at is not None else now
            start = job.started_at
            snap[f"job{jid}_wall_ms"] = (
                0.0 if start is None else round((end - start) * 1e3, 3))
        return snap
