"""Live runtime gauges: the PAPI-SDE counterpart.

Rebuild of the reference's software-defined-event exports (reference:
parsec/papi_sde.{c,h} — live gauges MEM_ALLOC/MEM_USED/TASKS_ENABLED/
TASKS_RETIRED/SCHEDULER_PENDING_TASKS readable by external consumers
while the runtime runs).  Counters update through PINS events plus
polling hooks; ``snapshot()`` is the external read.
"""

from __future__ import annotations

import threading
from typing import Dict


class Gauges:
    GAUGE_NAMES = ("tasks_enabled", "tasks_retired", "pending_tasks",
                   "device_bytes_in", "device_bytes_out",
                   "device_tasks", "device_evictions")

    def __init__(self):
        self._lock = threading.Lock()
        self.tasks_enabled = 0     # became ready (scheduled)
        self.tasks_retired = 0     # completed
        self.context = None

    def install(self, context) -> None:
        self.context = context
        context.pins_register("select", self._select)
        context.pins_register("complete_exec", self._complete)

    def uninstall(self, context) -> None:
        context.pins_unregister("select", self._select)
        context.pins_unregister("complete_exec", self._complete)
        self.context = None

    def _select(self, es, event, task) -> None:
        with self._lock:
            self.tasks_enabled += 1

    def _complete(self, es, event, task) -> None:
        with self._lock:
            self.tasks_retired += 1

    def snapshot(self) -> Dict[str, float]:
        snap = {
            "tasks_enabled": self.tasks_enabled,
            "tasks_retired": self.tasks_retired,
            "pending_tasks": max(0, self.tasks_enabled - self.tasks_retired),
            "device_bytes_in": 0,
            "device_bytes_out": 0,
            "device_tasks": 0,
            "device_evictions": 0,
        }
        ctx = self.context
        if ctx is not None:
            for d in ctx.device_registry.devices[1:]:
                snap["device_bytes_in"] += d.stats.bytes_in
                snap["device_bytes_out"] += d.stats.bytes_out
                snap["device_tasks"] += d.stats.executed_tasks
                snap["device_evictions"] += d.stats.evictions
        return snap


def install_gauges(context) -> Gauges:
    g = Gauges()
    g.install(context)
    return g
