"""Binary trace: per-stream event buffers with a typed dictionary.

Rebuild of the reference's profiling subsystem (reference:
parsec/profiling.c + parsec/parsec_binary_profile.h — per-thread
append-only buffers of fixed-size events {key, flags, taskpool_id,
event_id, timestamp} plus typed info payloads; a dictionary maps key ->
name + info-converter string; buffer types EVENTS/DICTIONARY/THREAD/
GLOBAL_INFO/HEADER, :29-33; API parsec_profiling_{init,start,fini},
_trace_flags, _dbp_dump, profiling.h:133-395).

Here an event is a struct-packed record; "info" payloads are key=value
dicts pickled per event when present (the reference's converter strings
describe C structs — the python-native equivalent is self-describing).
The writer is wait-free per stream: each stream appends to its own list;
dump() serializes header + dictionary + per-stream sections into one
.ptt file the reader (reader.py) loads into pandas — the pbt2ptt
pipeline's shape (tools/profiling/python/pbt2ptt.pyx).
"""

from __future__ import annotations

import io
import itertools
import os
import pickle
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

MAGIC = b"PTPT0001"
EV_START = 1 << 0     # event marks an interval start
EV_END = 1 << 1       # event marks an interval end
EV_POINT = 1 << 2     # standalone point event

_EV = struct.Struct("!HHIQqd")   # key, flags, taskpool_id, event_id,
                                 # object id (task key hash), timestamp


class EventClass:
    """Dictionary entry (reference: parsec_profiling_add_dictionary_keyword)."""

    __slots__ = ("name", "key", "attributes")

    def __init__(self, name: str, key: int, attributes: str = ""):
        self.name = name
        self.key = key
        self.attributes = attributes   # converter-string analog


class StreamBuffer:
    """Per-execution-stream event buffer (reference: per-thread profiling
    buffers; appending never takes a lock).

    Info-less events — the overwhelming majority — take the C trace-sink
    path when the pinsext extension builds (reference: profiling.c's
    record path — one fixed-size append, timestamp taken in C); the
    amortized ctypes-bulk path is the first fallback, a plain Python
    list the last.  Events carrying a Python info payload stay in the
    Python list; everything merges, ordered by timestamp, at dump time.
    """

    #: pending-list length that triggers a bulk flush into the native
    #: packed buffer (one ctypes crossing per chunk, not per event —
    #: the per-event hot path is ONE list append; sp-perf.c-class cost)
    FLUSH_CHUNK = 1024

    def __init__(self, stream_id: int, name: str):
        self.stream_id = stream_id
        self.name = name
        self.events: List[Tuple] = []
        self._pending: List[Tuple] = []
        self._native = None
        self._sink = None
        self._sink_interval = None
        try:
            from parsec_tpu.native import (NativeTraceBuffer, available,
                                           load_pinsext)
            px = load_pinsext()
            if px is not None:
                self._sink = px.TraceSink()
                # one-crossing interval append (VERDICT r5 #5); absent
                # on a stale prebuilt extension -> two-call fallback
                self._sink_interval = getattr(self._sink, "interval",
                                              None)
            elif available():
                self._native = NativeTraceBuffer()
        except Exception:   # toolchain missing: pure-Python path
            self._native = None
            self._sink = None

    def trace(self, key: int, flags: int, taskpool_id: int, event_id: int,
              object_id: int = 0, info: Any = None,
              timestamp: Optional[float] = None) -> None:
        if info is None:
            sink = self._sink
            if sink is not None:
                if timestamp is None:
                    # ONE C call; the timestamp is taken inside, on the
                    # same CLOCK_MONOTONIC timeline as perf_counter
                    sink.event(key, flags, taskpool_id, event_id,
                               object_id)
                else:
                    sink.event_at(key, flags, taskpool_id, event_id,
                                  object_id, timestamp)
                return
            if self._native is not None:
                ts = timestamp if timestamp is not None \
                    else time.perf_counter()
                self._pending.append((key, flags, taskpool_id, event_id,
                                      object_id, ts))
                if len(self._pending) >= self.FLUSH_CHUNK:
                    self.flush_native()
                return
        ts = timestamp if timestamp is not None else time.perf_counter()
        self.events.append((key, flags, taskpool_id, event_id, object_id,
                            ts, info))

    def interval(self, key: int, taskpool_id: int, event_id: int,
                 object_id: int, t_begin: float) -> None:
        """Both edges of one task interval in ONE call: the START record
        carries the caller-captured begin timestamp (perf_counter), the
        END record is stamped at call time.  With the C sink extension
        this is a single boundary crossing (pinsext interval, VERDICT
        r5 #5); otherwise it degrades to two plain records."""
        iv = self._sink_interval
        if iv is not None:
            iv(key, taskpool_id, event_id, object_id, t_begin,
               EV_START, EV_END)
            return
        self.trace(key, EV_START, taskpool_id, event_id, object_id,
                   timestamp=t_begin)
        self.trace(key, EV_END, taskpool_id, event_id, object_id)

    def flush_native(self) -> None:
        """Bulk-load pending info-less events into the native packed
        buffer (one boundary crossing per chunk)."""
        if self._pending and self._native is not None:
            pending, self._pending = self._pending, []
            self._native.events_bulk(pending)

    def merged_events(self) -> List[Tuple]:
        """All events (C sink / native buffer / python), timestamp-ordered."""
        if self._sink is not None:
            merged = [ev + (None,) for ev in self._sink.drain()]
            # a drained sink would lose events on a second call: keep
            # them in the python list so dump() stays idempotent
            self.events = merged + self.events
            self.events.sort(key=lambda e: e[5])
            return list(self.events)
        if self._native is None:
            # deferred-begin intervals append their START (earlier
            # timestamp) at END time: order by timestamp here too
            return sorted(self.events, key=lambda e: e[5])
        self.flush_native()
        merged = [ev + (None,) for ev in self._native.drain()]
        merged.extend(self.events)
        merged.sort(key=lambda e: e[5])
        return merged


class Profile:
    """One trace session (reference: parsec_profiling state)."""

    def __init__(self, hr_id: str = "parsec_tpu"):
        self.hr_id = hr_id
        self._dict: Dict[str, EventClass] = {}
        self._keys = itertools.count(1)
        self._streams: Dict[int, StreamBuffer] = {}
        self._lock = threading.Lock()
        self._info: Dict[str, str] = {}
        self._event_ids = itertools.count(1)
        self.enabled = True

    # -- dictionary -------------------------------------------------------
    def add_event_class(self, name: str, attributes: str = "") -> EventClass:
        with self._lock:
            ec = self._dict.get(name)
            if ec is None:
                ec = EventClass(name, next(self._keys), attributes)
                self._dict[name] = ec
            return ec

    def event_class(self, name: str) -> Optional[EventClass]:
        return self._dict.get(name)

    def add_information(self, key: str, value: str) -> None:
        self._info[key] = str(value)

    # -- streams ----------------------------------------------------------
    def stream(self, stream_id: int, name: str = "") -> StreamBuffer:
        with self._lock:
            sb = self._streams.get(stream_id)
            if sb is None:
                sb = StreamBuffer(stream_id, name or f"stream-{stream_id}")
                self._streams[stream_id] = sb
            return sb

    def next_event_id(self) -> int:
        return next(self._event_ids)

    # -- convenience: interval tracing ------------------------------------
    def trace_interval_start(self, sb: StreamBuffer, name: str,
                             taskpool_id: int, event_id: int,
                             object_id: int = 0, info: Any = None) -> None:
        if self.enabled:
            ec = self.add_event_class(name)
            sb.trace(ec.key, EV_START, taskpool_id, event_id, object_id,
                     info)

    def trace_interval_end(self, sb: StreamBuffer, name: str,
                           taskpool_id: int, event_id: int,
                           object_id: int = 0, info: Any = None) -> None:
        if self.enabled:
            ec = self.add_event_class(name)
            sb.trace(ec.key, EV_END, taskpool_id, event_id, object_id, info)

    # -- dump (reference: parsec_profiling_dbp_dump) ----------------------
    def dump(self, path: str) -> str:
        with self._lock:
            streams = list(self._streams.values())
            dico = list(self._dict.values())
        merged = {sb.stream_id: sb.merged_events() for sb in streams}
        buf = io.BytesIO()
        buf.write(MAGIC)
        meta = {
            "hr_id": self.hr_id,
            "info": self._info,
            "dictionary": [(ec.key, ec.name, ec.attributes) for ec in dico],
            "streams": [(sb.stream_id, sb.name, len(merged[sb.stream_id]))
                        for sb in streams],
        }
        mb = pickle.dumps(meta, protocol=pickle.HIGHEST_PROTOCOL)
        buf.write(struct.pack("!Q", len(mb)))
        buf.write(mb)
        for sb in streams:
            infos = {}
            for i, (key, flags, tp, eid, oid, ts, info) in \
                    enumerate(merged[sb.stream_id]):
                buf.write(_EV.pack(key, flags, tp, eid, oid, ts))
                if info is not None:
                    infos[i] = info
            ib = pickle.dumps(infos, protocol=pickle.HIGHEST_PROTOCOL)
            buf.write(struct.pack("!Q", len(ib)))
            buf.write(ib)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(buf.getvalue())
        return path


_profile: Optional[Profile] = None


def profiling_init(hr_id: str = "parsec_tpu") -> Profile:
    """reference: parsec_profiling_init (profiling.c:473)."""
    global _profile
    _profile = Profile(hr_id)
    return _profile


def profiling_get() -> Optional[Profile]:
    return _profile


def profiling_fini(path: Optional[str] = None) -> Optional[str]:
    """Dump and drop the session (reference: parsec_profiling_fini)."""
    global _profile
    p = _profile
    _profile = None
    if p is not None and path is not None:
        return p.dump(path)
    return None
