"""DOT grapher: emit the executed DAG.

Rebuild of the reference's profiling grapher (reference:
parsec/parsec_prof_grapher.{c,h} — one DOT file per rank recording every
executed task as a node and every resolved dependency as an edge, enabled
with ``--mca parsec_dot``).  Nodes record task class + parameters and the
stream that ran them; edges record the flow names they rode.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class DotGrapher:
    """Collects nodes/edges; installed on a context as ``ctx.grapher``
    (the dep engine notifies it during release_deps)."""

    def __init__(self, rank: int = 0):
        self.rank = rank
        self._lock = threading.Lock()
        self._nodes: Dict[Tuple, Dict] = {}
        self._edges: List[Tuple[Tuple, Tuple, str]] = []

    def install(self, context) -> None:
        context.grapher = self
        context.pins_register("complete_exec", self._complete)

    def _complete(self, es, event, task) -> None:
        with self._lock:
            self._nodes[task.key] = {
                "label": repr(task),
                "stream": es.th_id,
                "tc": task.task_class.name,
            }

    def edge(self, src_task, dst_key: Tuple, flow_name: str) -> None:
        """Called by the dep engine for every task->task dep resolved."""
        with self._lock:
            self._edges.append((src_task.key, dst_key, flow_name))

    def dump(self, path: str) -> str:
        def nid(key: Tuple) -> str:
            return "t_" + "_".join(str(k) for k in key)
        lines = [f'digraph rank{self.rank} {{']
        with self._lock:
            for key, attrs in self._nodes.items():
                lines.append(
                    f'  {nid(key)} [label="{attrs["label"]}",'
                    f'tooltip="stream {attrs["stream"]}"];')
            for src, dst, flow in self._edges:
                lines.append(f'  {nid(src)} -> {nid(dst)} '
                             f'[label="{flow}"];')
        lines.append("}")
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        return path
