"""Always-on telemetry: metrics registry + Prometheus scrape surface.

The production counterpart of the reference's PAPI-SDE live counters
(reference: parsec/papi_sde.{c,h} — software-defined events external
agents read while the runtime serves).  prof/gauges.py rebuilt those
counters; this module grows them into a telemetry PLANE:

* a lock-cheap registry of Counter / Gauge / Histogram metrics with
  labeled families (per-peer, per-device-class, per-job), fed from the
  existing PINS / ``CommEngine.stats`` / ``RemoteDepEngine.stats()`` /
  JobGauges paths;
* Histograms use FIXED log2 latency buckets (one ``frexp`` per
  observation, no bucket search) plus a small ring reservoir for
  quantile estimates;
* hot-path counters are sampled (``metrics_sample``): the per-task cost
  is two PINS dispatches and one short lock hold — the premerge
  telemetry-overhead gate bounds the whole plane at <= 5% of the tasks
  probe (vs ~30% for the full causal tracer);
* ``samples()`` snapshots everything into a wire-friendly list;
  ``render_text()`` emits Prometheus text exposition;
  ``merge_samples`` folds per-rank snapshots into one cluster view
  (counters/histograms sum, gauges keep a ``rank`` label) — the
  TAG_METRICS pull in comm/engine.py ships peer snapshots so one
  scrape sees the mesh.

Installed by default on every Context (``metrics_enabled``); scraped
through the JobServer's ``{"op": "metrics"}`` request or a plain HTTP
``GET /metrics`` on the same port (service/server.py), or the
``tools/metrics_client.py`` CLI.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from parsec_tpu.utils.mca import params

params.register("metrics_enabled", 1,
                "install the always-on telemetry registry on every "
                "Context: task/comm/device/job counter families plus "
                "latency histograms, scrapeable through the job "
                "server's /metrics surface and aggregated across ranks "
                "over TAG_METRICS (0 disables every hook)")
params.register("metrics_sample", 16,
                "histogram sampling stride for per-task latency and "
                "queue-wait observations: 1 observes every task, N "
                "observes one in N (counters stay exact; sampling only "
                "thins the histogram population to keep the always-on "
                "cost inside the premerge <=5% telemetry gate)")
params.register("metrics_queue_wait", 0,
                "split the task-latency telemetry: hook the select + "
                "exec_begin/exec_end PINS events too, so queue-wait "
                "(ready->select) and body execution latency "
                "(exec_begin->exec_end, the same interval the task "
                "profiler records) are separate histograms — also "
                "what the live attribution plane needs for a true "
                "exec/queue split.  Default off — each additional "
                "hooked event costs tasks-probe budget; the default "
                "single-hook path folds everything into the "
                "sojourn-time latency histogram (ready->complete), "
                "which is what a serving SLO reads anyway")
params.register("metrics_ring", 256,
                "per-histogram quantile reservoir size: the most recent "
                "N observations kept in a ring for q50/q99 estimates "
                "(bucket counts are exact regardless)")
params.register("metrics_slo_job_s", 0.0,
                "job admission->completion SLO in seconds: a finished "
                "job over budget counts in jobs_slo_breached_total and "
                "— with the flight recorder armed — triggers an "
                "incident dump (0 disables the breach trigger)")

#: log2 histogram bucket bounds: 2^-20 s (~1 us) .. 2^6 s (64 s).
#: Fixed at module scope so every rank's buckets merge positionally.
_LOW = -20
_NBUCKETS = 27
BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    float(2.0 ** (_LOW + i)) for i in range(_NBUCKETS))


def bucket_index(x: float) -> int:
    """Index of the smallest bound >= x (len(BUCKET_BOUNDS) = +Inf).
    One frexp, no search: x = m * 2^e with m in [0.5, 1) puts x under
    bound 2^e — except exact powers of two (m == 0.5), which belong one
    bucket down (le semantics: count of observations <= bound)."""
    if x <= BUCKET_BOUNDS[0]:
        return 0
    m, e = math.frexp(x)
    i = e - _LOW - (1 if m == 0.5 else 0)
    return i if i < _NBUCKETS else _NBUCKETS


class Counter:
    """Monotonic counter.  ``inc`` takes one short lock hold — cheap
    enough for per-task paths, exact under every thread interleaving."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0                    # guarded-by: _lock

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        return self._v


class Gauge:
    """Point-in-time value (set/add); reads are snapshot-racy by
    design, like the reference's SDE counters."""

    __slots__ = ("_lock", "_v")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0                    # guarded-by: _lock

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        return self._v


class Histogram:
    """Fixed log2-bucket histogram + ring reservoir for quantiles.

    ``observe`` is one lock hold around four scalar updates; bucket
    selection is a single ``frexp`` (no search), so the latency classes
    this serves (task latency, queue wait, frame RTT, job SLO) cost the
    same regardless of magnitude."""

    __slots__ = ("_lock", "buckets", "sum", "count", "_ring", "_rn")

    def __init__(self, ring: Optional[int] = None):
        self._lock = threading.Lock()
        #: raw (non-cumulative) per-bucket counts; index NBUCKETS = +Inf
        #: (guarded-by: _lock)
        self.buckets = [0] * (_NBUCKETS + 1)
        self.sum = 0.0                   # guarded-by: _lock
        self.count = 0                   # guarded-by: _lock
        n = ring if ring is not None \
            else max(16, int(params.get("metrics_ring", 256)))
        self._ring: List[float] = [0.0] * n   # guarded-by: _lock
        self._rn = 0                     # guarded-by: _lock

    def observe(self, x: float) -> None:
        i = bucket_index(x)
        with self._lock:
            self.buckets[i] += 1
            self.sum += x
            self.count += 1
            self._ring[self._rn % len(self._ring)] = x
            self._rn += 1

    def quantile(self, q: float) -> float:
        """Estimate from the ring reservoir (recent-window quantile)."""
        with self._lock:
            n = min(self._rn, len(self._ring))
            snap = sorted(self._ring[:n])
        if not snap:
            return 0.0
        return snap[min(len(snap) - 1, int(q * len(snap)))]

    def snapshot(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self.buckets), self.sum, self.count


class Family:
    """Labeled metric family: ``family.labels(peer="1")`` returns the
    child metric, created on demand.  Bounded: past ``max_series`` the
    oldest-inserted child is dropped (a resident service must not grow
    O(label cardinality))."""

    def __init__(self, kind: type, label_names: Tuple[str, ...],
                 max_series: int, **kw):
        self.kind = kind
        self.label_names = label_names
        self._kw = kw
        self._max = max_series
        self._lock = threading.Lock()
        #: label-value tuple -> metric (guarded-by: _lock)
        self._children: Dict[Tuple[str, ...], Any] = {}

    def labels(self, **labels) -> Any:
        key = tuple(str(labels[n]) for n in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self.kind(**self._kw)
                while len(self._children) > self._max:
                    self._children.pop(next(iter(self._children)))
            return child

    def items(self) -> List[Tuple[Dict[str, str], Any]]:
        with self._lock:
            kids = list(self._children.items())
        return [(dict(zip(self.label_names, key)), m) for key, m in kids]


# ---------------------------------------------------------------------------
# sample records: the wire/merge/render interchange form
# ---------------------------------------------------------------------------

def counter_sample(name: str, value: float,
                   labels: Optional[Dict[str, str]] = None) -> dict:
    return {"n": name, "t": "counter", "l": dict(labels or {}),
            "v": float(value)}


def gauge_sample(name: str, value: float,
                 labels: Optional[Dict[str, str]] = None) -> dict:
    return {"n": name, "t": "gauge", "l": dict(labels or {}),
            "v": float(value)}


def histogram_sample(name: str, hist: Histogram,
                     labels: Optional[Dict[str, str]] = None) -> dict:
    buckets, s, c = hist.snapshot()
    return {"n": name, "t": "histogram", "l": dict(labels or {}),
            "b": buckets, "sum": s, "cnt": c}


def merge_samples(per_rank: Dict[int, List[dict]]) -> List[dict]:
    """Fold per-rank sample lists into one cluster view: counters and
    histograms SUM across ranks (positional log2 buckets make that
    exact); gauges are point-in-time per-rank readings, so each keeps
    its origin as a ``rank`` label."""
    merged: Dict[Tuple, dict] = {}
    for rank in sorted(per_rank):
        for s in per_rank[rank]:
            if s.get("t") == "section":
                # non-metric side-channel records (the liveattr status
                # section) ride the same pull but never merge or render
                continue
            labels = dict(s.get("l") or {})
            if s["t"] == "gauge":
                labels["rank"] = str(rank)
            key = (s["n"], s["t"], tuple(sorted(labels.items())))
            cur = merged.get(key)
            if cur is None:
                cur = merged[key] = {**s, "l": labels}
                if s["t"] == "histogram":
                    cur["b"] = list(s["b"])
                continue
            if s["t"] == "histogram":
                for i, b in enumerate(s["b"]):
                    cur["b"][i] += b
                cur["sum"] += s["sum"]
                cur["cnt"] += s["cnt"]
            else:
                cur["v"] += s["v"]
    return list(merged.values())


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in sorted(labels.items()))
    return "{%s}" % inner


def _fmt_num(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_text(samples: List[dict]) -> str:
    """Prometheus text exposition (0.0.4): HELP/TYPE once per family,
    histogram buckets CUMULATIVE with le labels + _sum/_count."""
    by_name: Dict[str, List[dict]] = {}
    for s in samples:
        if s.get("t") == "section":   # side-channel records don't render
            continue
        by_name.setdefault(s["n"], []).append(s)
    out: List[str] = []
    for name in sorted(by_name):
        group = by_name[name]
        typ = group[0]["t"]
        out.append(f"# TYPE {name} {typ}")
        for s in group:
            labels = s.get("l") or {}
            if typ == "histogram":
                cum = 0
                for i, b in enumerate(s["b"]):
                    cum += b
                    le = ("+Inf" if i >= len(BUCKET_BOUNDS)
                          else repr(BUCKET_BOUNDS[i]))
                    out.append("%s_bucket%s %d" % (
                        name, _fmt_labels({**labels, "le": le}), cum))
                out.append("%s_sum%s %s" % (name, _fmt_labels(labels),
                                            _fmt_num(s["sum"])))
                out.append("%s_count%s %d" % (name, _fmt_labels(labels),
                                              s["cnt"]))
            else:
                out.append("%s%s %s" % (name, _fmt_labels(labels),
                                        _fmt_num(s["v"])))
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# the runtime installer: PINS hooks + scrape-time collectors
# ---------------------------------------------------------------------------

class _StrideGated:
    """complete_exec callback wrapper advertising its sampling stride
    to the native worker quantum (schedext.run_quantum): when
    ``es.nb_tasks_done % __pins_stride__`` is nonzero the C dispatcher
    skips the call entirely — exactly equivalent to the wrapped
    handler's own unsampled early-return (which touches nothing, not
    even liveattr), but without the per-task Python call.  Split mode
    (``metrics_queue_wait=1``) does real work on every event, so the
    property answers stride 1 there (= never skip); the Python
    dispatch path ignores the attribute and calls through unchanged."""

    __slots__ = ("fn", "_m")

    def __init__(self, fn, metrics):
        self.fn = fn
        self._m = metrics

    @property
    def __pins_stride__(self) -> int:
        m = self._m
        return 1 if m._split_queue else m._sample

    def __call__(self, es, event, task):
        return self.fn(es, event, task)


class RuntimeMetrics:
    """One per Context.  Live hot-path metrics (task counters, sampled
    latency/queue-wait histograms, job SLO histograms) update through
    PINS; everything already counted elsewhere — ``CommEngine.stats``,
    ``RemoteDepEngine.stats()``, device stats, JobGauges — is read at
    SCRAPE time by collectors, so steady state pays nothing for it
    (the PAPI-SDE pattern: the counter is the source of truth, the
    exporter just reads it)."""

    def __init__(self, rank: int = 0):
        self.rank = rank
        self.context = None
        self._service = None
        self._lock = threading.Lock()
        self._sample = max(1, int(params.get("metrics_sample", 16)))
        self._split_queue = bool(int(params.get("metrics_queue_wait", 0)))
        #: opt-in select/exec-hook sampling strides (racy ints:
        #: approximate stride is fine, the samples are a reservoir)
        self._sn = 0
        self._en = 0
        #: discards are rare (pool cancellation) — a locked counter
        #: costs nothing at steady state
        self._discarded = Counter()
        self.task_latency = Histogram()
        self.task_queue_wait = Histogram()
        self.job_duration = Histogram()
        self.job_queue = Histogram()
        self.comm_frame_rtt = Histogram()
        self._jobs_done = Family(Counter, ("status",), 16)
        self._slo = float(params.get("metrics_slo_job_s", 0.0))
        self._slo_breached = Counter()
        self._collectors: List[Callable[[], List[dict]]] = []
        #: online attribution engine (prof/liveattr.py) riding THESE
        #: hooks — it registers no PINS callbacks of its own
        self._la = None
        #: predictive health plane (prof/health.py): scrape-time
        #: fusion of the existing counters — no hooks, no hot path
        self._health = None
        #: the stride-advertising wrapper _complete registers through
        #: (built at install; the native quantum reads its stride)
        self._complete_cb = None

    # -- lifecycle -------------------------------------------------------
    @property
    def liveattr(self):
        """The online attribution engine, or None when disarmed."""
        return self._la

    @property
    def health(self):
        """The predictive health monitor, or None when disarmed."""
        return self._health

    def install(self, context) -> "RuntimeMetrics":
        self.rank = context.rank
        self.context = context
        context.metrics = self
        context._recompute_ready_stamp()
        if int(params.get("liveattr_enable", 1)):
            from parsec_tpu.prof.liveattr import LiveAttr
            self._la = LiveAttr(self)
        if int(params.get("health_enable", 1)):
            from parsec_tpu.prof.health import HealthMonitor
            self._health = HealthMonitor(self)
        # ONE hooked hot-path event by default: every additional PINS
        # dispatch with a live callback costs ~0.5us/task on the tasks
        # probe — two hooks alone would eat the whole armed budget
        if self._split_queue:
            context.pins_register("select", self._select)
            context.pins_register("exec_begin", self._exec_begin)
            context.pins_register("exec_end", self._exec_end)
        # registered through a stride-advertising wrapper: the native
        # run_quantum reads __pins_stride__ and SKIPS the unsampled
        # calls entirely (valid because _complete's unsampled
        # single-hook path is a pure no-op — it returns before
        # touching liveattr; split mode advertises stride 1)
        self._complete_cb = _StrideGated(self._complete, self)
        context.pins_register("complete_exec", self._complete_cb)
        context.pins_register("task_discard", self._discard)
        context.pins_register("job_done", self._job_done)
        ce = self._ce(context)
        if ce is not None:
            ce.metrics_provider = self.samples
            ce.on_clock_rtt = self.comm_frame_rtt.observe
        return self

    @staticmethod
    def _ce(context):
        comm = getattr(context, "comm", None)
        return getattr(comm, "ce", None) if comm is not None else None

    def uninstall(self, context) -> None:
        if self._split_queue:
            context.pins_unregister("select", self._select)
            context.pins_unregister("exec_begin", self._exec_begin)
            context.pins_unregister("exec_end", self._exec_end)
        context.pins_unregister("complete_exec", self._complete_cb)
        context.pins_unregister("task_discard", self._discard)
        context.pins_unregister("job_done", self._job_done)
        ce = self._ce(context)
        if ce is not None and ce.metrics_provider == self.samples:
            # a detached registry must not keep serving TAG_METRICS
            ce.metrics_provider = None
            ce.on_clock_rtt = None
        if getattr(context, "metrics", None) is self:
            context.metrics = None
            context._recompute_ready_stamp()
        self.context = None
        self._la = None   # cached per-TaskClass recs detect the
        #                   staleness through their rec.la identity
        self._health = None

    def attach_service(self, service) -> None:
        """Job-service gauges (pending/running/degraded + the bounded
        per-job task counters JobGauges already keeps) join the scrape."""
        self._service = service

    def detach_service(self, service) -> None:
        if self._service is service:
            self._service = None

    def register_collector(self, fn: Callable[[], List[dict]]) -> None:
        self._collectors.append(fn)

    # -- PINS hot path ---------------------------------------------------
    # The retired counter is NOT kept here: complete_execution already
    # maintains ExecutionStream.nb_tasks_done, so the scrape sums that
    # for free and the hot handler only pays the sampling stride — an
    # attribute read, a modulo, and (one task in N) a perf_counter +
    # histogram observe.  That is what keeps the whole armed plane
    # inside the premerge <=5% gate.

    def _select(self, es, event, task) -> None:
        # opt-in (metrics_queue_wait=1): split queue-wait from exec
        n = self._sn = self._sn + 1
        qw = None
        if not n % self._sample:
            now = time.perf_counter()
            t0 = task.ready_at
            if t0 is not None and t0 <= now:
                qw = now - t0
                self.task_queue_wait.observe(qw)
        la = self._la
        if la is not None:
            # liveattr rides this hook: exact per-class selection
            # counts, the sampled queue-wait profile, and the armed
            # queue-side straggler check
            la.task_selected(task, qw)

    def _exec_begin(self, es, event, task,
                    _perf=time.perf_counter) -> None:
        # split mode only: stamp the body interval's start — the SAME
        # interval the task profiler records, so the online exec
        # bucket means what the offline critpath exec bucket means
        task.mtr_t0 = _perf()

    def _exec_end(self, es, event, task,
                  _perf=time.perf_counter) -> None:
        t0 = task.mtr_t0
        if t0 is None:
            return
        task.mtr_t0 = None
        dt = _perf() - t0
        n = self._en = self._en + 1
        sampled = not n % self._sample
        if sampled:
            self.task_latency.observe(dt)
        la = self._la
        if la is not None:
            # exec profile + the exec-side straggler check live here
            # (complete_exec fires after release_deps, so a
            # select->complete clock would fold dep-release and
            # activation-pack time into 'exec')
            la.observe_exec(task, dt, sampled)

    def _complete(self, es, event, task,
                  _perf=time.perf_counter) -> None:
        # default-bound locals: this runs once per task on every
        # stream — each saved attribute lookup is premerge-gate budget
        la = self._la
        sampled = not es.nb_tasks_done % self._sample   # stream-local
        if self._split_queue:
            if task.mtr_t0 is not None:
                # ASYNC (device) task: exec_end never ran on a worker
                # stream — close the interval here
                self._exec_end(es, event, task)
            if la is not None:
                # split mode opted into per-task cost: exact done
                # counts; the straggler check already ran at exec_end
                la.task_done(la.rec_of(task), es, task, sampled,
                             check=False)
            return
        if not sampled:
            # the common case pays liveattr NOTHING: counts, profiles
            # and the straggler check all ride the sampling stride,
            # exactly like the latency histogram below this line
            return
        # single-hook mode: the sampled observation is the SOJOURN time
        # (ready->complete, what an SLO reads); Task.ready_at is the
        # scheduler's stamp, still set unless a causal tracer consumed
        # it (which provides strictly richer data)
        t0 = task.ready_at
        if t0 is not None:
            now = _perf()
            if t0 <= now:
                self.task_latency.observe(now - t0)
        if la is not None:
            la.task_done(la.rec_of(task), es, task, True)

    def _discard(self, es, event, task) -> None:
        self._discarded.inc()

    # -- job lifecycle (service/service.py _emit; jobs_submitted derives
    # from the service collector, so only job_done is hooked) ------------
    def _job_done(self, es, event, job) -> None:
        # fired EXACTLY ONCE per job (JobService._emit_done's one-shot
        # seam): a recovery restart re-terminating a completed pool is
        # absorbed below the service, so the SLO histograms and the
        # per-status counters never double-observe a job
        try:
            status = job.status().name.lower()
            self._jobs_done.labels(status=status).inc()
            sub, start, end = job.submitted_mono, job.started_at, \
                job.finished_at
            if start is not None and end is not None:
                # started_at/finished_at are wall-clock; their
                # difference is the run time, and queue time falls out
                # of the monotonic submission stamp
                run_s = max(0.0, end - start)
                total_s = max(run_s, time.monotonic() - sub)
                self.job_queue.observe(max(0.0, total_s - run_s))
                self.job_duration.observe(total_s)
                if self._slo > 0 and total_s > self._slo:
                    self._slo_breached.inc()
                    ctx = self.context
                    if ctx is not None:
                        ctx.telemetry_incident(
                            f"job {job.job_id} breached the "
                            f"{self._slo:g}s SLO ({total_s:.2f}s)")
        except Exception:   # telemetry must never fail a job callback
            pass

    def _pending_tasks(self) -> int:
        ctx = self.context
        if ctx is None:
            return 0
        with ctx._lock:
            pools = list(ctx.taskpools.values())
        return sum(max(0, int(getattr(tp, "nb_tasks", 0) or 0))
                   for tp in pools
                   if not getattr(tp, "completed", False)
                   and not getattr(tp, "cancelled", False))

    # -- scrape ----------------------------------------------------------
    def samples(self) -> List[dict]:
        ctx = self.context
        # retired rides the streams' own nb_tasks_done (maintained by
        # complete_execution regardless of telemetry — the PAPI-SDE
        # pattern: read the counter that already exists)
        retired = sum(es.nb_tasks_done for es in ctx.streams) \
            if ctx is not None else 0
        discarded = int(self._discarded.value)
        # pending is a GAUGE, never folded into a *_total counter: a
        # failed pool leaving the registry legitimately shrinks it, and
        # a decreasing counter reads as a reset to rate()-style queries
        out = [
            counter_sample("parsec_tasks_retired_total", retired),
            counter_sample("parsec_tasks_discarded_total", discarded),
            gauge_sample("parsec_pending_tasks", self._pending_tasks()),
            histogram_sample("parsec_task_latency_seconds",
                             self.task_latency),
            histogram_sample("parsec_task_queue_wait_seconds",
                             self.task_queue_wait),
            histogram_sample("parsec_job_duration_seconds",
                             self.job_duration),
            histogram_sample("parsec_job_queue_seconds", self.job_queue),
            histogram_sample("parsec_comm_frame_rtt_seconds",
                             self.comm_frame_rtt),
            counter_sample("parsec_jobs_slo_breached_total",
                           self._slo_breached.value),
        ]
        for labels, c in self._jobs_done.items():
            out.append(counter_sample("parsec_jobs_done_total", c.value,
                                      labels))
        la = self._la
        if la is not None:
            # straggler counters + the liveattr status section (a
            # side-channel record the render/merge paths skip): the
            # cross-rank status document rides the SAME TAG_METRICS
            # pull as the /metrics scrape — zero new wire tags
            out.extend(la.samples())
            try:
                out.append({"n": "__liveattr__", "t": "section",
                            "l": {}, "doc": la.section()})
            except Exception:   # the side channel must not kill scrape
                pass
        hm = self._health
        if hm is not None:
            # per-rank health gauges + the __health__ status section —
            # the fold itself is rate-limited inside refresh(), so a
            # scrape storm costs one dict walk, not one re-score
            try:
                hm.refresh()
                out.extend(hm.samples())
                out.append({"n": "__health__", "t": "section",
                            "l": {}, "doc": hm.section()})
            except Exception:   # the side channel must not kill scrape
                pass
        out.extend(self._collect_comm())
        out.extend(self._collect_sched())
        out.extend(self._collect_devices())
        out.extend(self._collect_service())
        for fn in list(self._collectors):
            try:
                out.extend(fn())
            except Exception:   # a broken collector must not kill scrape
                pass
        return out

    def _collect_comm(self) -> List[dict]:
        ctx = self.context
        comm = getattr(ctx, "comm", None) if ctx is not None else None
        if comm is None:
            return []
        out: List[dict] = []
        try:
            st = comm.stats()
        except Exception:
            return []
        for key in ("frames_sent", "frames_recv", "bytes_sent",
                    "bytes_recv", "syscalls_send", "syscalls_recv",
                    "act_eager", "act_rdv", "act_inline",
                    "eager_bytes", "rdv_bytes", "coalesced_msgs",
                    "eager_downshift", "eager_upshift",
                    # r11 native/shm data-plane counters (all
                    # maintained on their existing hot paths; this
                    # read is scrape-time only): frames through the C
                    # parser, shm ring backpressure stalls, doorbell
                    # traffic in each direction
                    "frames_parsed_native", "shm_ring_full_stalls",
                    "shm_doorbells_sent", "shm_doorbells_recv"):
            v = st.get(key)
            if isinstance(v, (int, float)):
                out.append(counter_sample(f"parsec_comm_{key}_total", v))
        ce = getattr(comm, "ce", None)
        if ce is None:
            return out
        out.append(gauge_sample("parsec_comm_dead_peers",
                                len(ce.dead_peers)))
        try:
            for r, info in ce.peer_debug().items():
                age = info.get("last_heard_age_s")
                if age is not None:
                    out.append(gauge_sample(
                        "parsec_comm_peer_silence_seconds", age,
                        {"peer": str(r)}))
            for r, n in ce.hb_rebases().items():
                out.append(counter_sample("parsec_comm_hb_rebase_total",
                                          n, {"peer": str(r)}))
            for r, stc in ce.clock_table().items():
                out.append(gauge_sample("parsec_comm_clock_rtt_seconds",
                                        stc.get("rtt", 0.0),
                                        {"peer": str(r)}))
        except Exception:
            pass
        return out

    def _collect_sched(self) -> List[dict]:
        """Native-scheduler family, read at scrape time from the C
        queue's own counters (sched/native.py stats()) — zero work on
        the schedule/select hot path."""
        ctx = self.context
        sched = getattr(ctx, "scheduler", None) if ctx is not None \
            else None
        out: List[dict] = []
        try:
            from parsec_tpu.sched.native import fallbacks
            out.append(counter_sample(
                "parsec_sched_native_fallbacks_total", fallbacks()))
        except Exception:
            pass
        st_fn = getattr(sched, "stats", None)
        if st_fn is None:
            return out
        try:
            st = st_fn()
        except Exception:
            return out
        out.append(counter_sample("parsec_sched_native_pushes_total",
                                  st.get("pushes", 0)))
        out.append(counter_sample("parsec_sched_native_pops_total",
                                  st.get("pops", 0)))
        out.append(gauge_sample("parsec_sched_native_pending",
                                st.get("pending", 0)))
        # per-reason fast-path bailouts: the attribution for "why is the
        # C chain not taking my tasks" — a comm_buffered or non_trivial
        # spike localizes a coverage regression without a bench rerun
        try:
            from parsec_tpu.native import load_schedext
            se = load_schedext()
            bail_fn = getattr(se, "bailout_stats", None)
            if bail_fn is not None:
                for reason, n in sorted(bail_fn().items()):
                    if n:
                        out.append(counter_sample(
                            "parsec_sched_native_bailouts_total", n,
                            {"reason": reason}))
        except Exception:
            pass
        return out

    def _collect_devices(self) -> List[dict]:
        ctx = self.context
        if ctx is None:
            return []
        out: List[dict] = []
        for d in ctx.device_registry.devices:
            st = getattr(d, "stats", None)
            if st is None:
                continue
            labels = {"device": getattr(d, "name", "?")}
            for key, metric in (
                    ("executed_tasks", "parsec_device_tasks_total"),
                    ("bytes_in", "parsec_device_bytes_in_total"),
                    ("bytes_out", "parsec_device_bytes_out_total"),
                    ("evictions", "parsec_device_evictions_total"),
                    ("chained_launches",
                     "parsec_device_chained_launches_total"),
                    ("chained_tasks", "parsec_device_chained_tasks_total")):
                v = getattr(st, key, None)
                if isinstance(v, (int, float)) and v:
                    out.append(counter_sample(metric, v, labels))
        return out

    def _collect_service(self) -> List[dict]:
        svc = self._service
        if svc is None:
            return []
        out: List[dict] = []
        try:
            st = svc.stats()
            out.append(gauge_sample("parsec_jobs_pending", st["pending"]))
            out.append(gauge_sample("parsec_jobs_running", st["running"]))
            out.append(counter_sample("parsec_jobs_submitted_total",
                                      st["total"]))
            out.append(gauge_sample("parsec_service_degraded",
                                    1.0 if st["degraded"] else 0.0))
            # per-job task counters ride the existing JobGauges path
            # (bounded to its max_jobs window) — all three columns,
            # distinguished by the kind label
            for jid, row in svc.gauges.job_task_rows():
                for kind, v in zip(("enabled", "retired", "discarded"),
                                   row):
                    if v:
                        out.append(counter_sample(
                            "parsec_job_tasks_total", v,
                            {"job": str(jid), "kind": kind}))
        except Exception:
            pass
        return out


def install_metrics(context) -> RuntimeMetrics:
    return RuntimeMetrics(rank=context.rank).install(context)


# ---------------------------------------------------------------------------
# cluster scrape: local samples + TAG_METRICS peer pulls, rendered
# ---------------------------------------------------------------------------

def cluster_exposition(context, aggregate: bool = True,
                       timeout: float = 2.0) -> Tuple[str, List[int]]:
    """One scrape: this rank's samples plus — on a multi-rank context
    with ``aggregate`` — every live peer's, pulled over the TAG_METRICS
    control lane and merged (counters/histograms sum, gauges keep a
    rank label).  Returns (exposition text, ranks included)."""
    m = getattr(context, "metrics", None)
    local = m.samples() if m is not None else []
    comm = getattr(context, "comm", None)
    ce = getattr(comm, "ce", None) if comm is not None else None
    if not aggregate or ce is None or context.nranks <= 1:
        return render_text(local), [context.rank]
    per_rank = {context.rank: local}
    try:
        per_rank.update(ce.gather_metrics(timeout=timeout))
    except Exception:   # scrape degrades to the local view, never fails
        pass
    return render_text(merge_samples(per_rank)), sorted(per_rank)
