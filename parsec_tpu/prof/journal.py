"""Control-plane black box: an always-on structured protocol journal.

The data plane is well-observed (causal traces, /metrics, the flight
recorder's event ring, live attribution) — but the runtime's hardest
bugs live in its *protocols*: the multi-round distributed control
plane PRs 9/11/14 grew (dead-set agreement, replay mode votes, DTD
insert-stream skip agreement, bounded need negotiation, the
retirement handshake, TAG_REJOIN incarnation fencing, epoch fences,
barrier generations) has documented residual failure modes — the
coordinator dying mid-handshake silently degrades the retirement
quorum to the grace window — that no existing surface records: when a
recovery round goes sideways there is no record of who voted what, in
which round, under which epoch.

This module is that record.  Same engineering discipline as the
metrics registry (prof/metrics.py):

* a ``Journal`` is installed on EVERY Context (``journal_enabled``,
  default 1): a bounded ring of small dicts, appended with one
  ``deque.append`` under the GIL (lock-free, no spill) plus a
  ``perf_counter`` stamp — the same timeline TAG_CLOCK aligns, so
  per-rank journals merge onto rank 0's clock exactly like traces;
* every emit site is CONTROL-PLANE code (recovery rounds, termdet
  rewinds, rejoin handshakes, barrier generations, job lifecycle
  decisions) — there are no per-task emits, so the C ``run_quantum``
  fast path never crosses this module (the premerge journal-overhead
  gate proves it);
* each event carries the common stamps (rank, incarnation epoch,
  monotonic seq) plus the schema'd protocol fields below — pool
  run_epoch, round id, peer set — so the offline auditor
  (tools/journal_audit.py) can check protocol INVARIANTS instead of
  eyeballing logs;
* journals are pulled cross-rank over the job port (``{"op":
  "journal"}`` — the pull rides the TAG_METRICS control lane, zero
  new wire tags) and every flight-recorder incident bundle includes
  ``journal-rank<N>.jsonl`` next to the event ring, so an incident
  dump carries the control-plane story next to the data-plane one.

Event-schema table (``EVENT_SCHEMA``): every ``journal.emit("<type>",
...)`` literal in the tree must appear here with its required fields
— parseclint's PCL-JRNL pass enforces it, and requires ``round=`` on
every round-scoped protocol emit (the schema-drift bug class: an
emit the auditor cannot attribute to a round is an emit the auditor
cannot check).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from parsec_tpu.utils.mca import params

params.register("journal_enabled", 1,
                "install the always-on control-plane journal on every "
                "Context: a bounded ring of structured protocol events "
                "(recovery rounds, termdet rewinds, retirement "
                "handshakes, rejoin fencing, barrier generations, job "
                "lifecycle), pullable over the job port and included "
                "in flight-recorder incident bundles (0 disables "
                "every emit)")
params.register("journal_ring", 4096,
                "journal ring capacity in EVENTS (bounded memory: "
                "oldest events are overwritten; control-plane rates "
                "are low, so the default holds whole recovery "
                "histories)")
params.register("journal_dir", "",
                "when set, every Context APPENDS its journal to "
                "<dir>/journal-rank<N>.jsonl at fini — the per-rank "
                "bundle tools/journal_audit.py merges and audits "
                "(chaos --audit-journal arms this per case)")
params.register("journal_autopsy_tail", 20,
                "control-plane events per rank the hang autopsy "
                "prints (clock-aligned, newest last) so a wedged "
                "negotiation is visible in the autopsy text without "
                "pulling bundles (0 disables the section)")

#: The event-schema table: type -> REQUIRED emit fields.  PCL-JRNL
#: checks every ``journal.emit("<type>")`` literal in the tree against
#: this table and requires each listed field as an explicit kwarg —
#: in particular ``round`` on every round-scoped protocol event.
#: Fields beyond the required ones are free-form context.
EVENT_SCHEMA: Dict[str, tuple] = {
    # termdet epoch transitions and rewind fences (core/recovery.py)
    "epoch_fence": ("pool", "epoch"),
    "termdet_rewind": ("pool", "was"),
    "safra_reconcile": ("peer",),
    # peer lifecycle (comm/engine.py)
    "peer_dead": ("peer", "detector"),
    "peer_excused": ("peer",),
    # dead-set agreement round (TAG_RECOVER)
    "deadset_report": ("peers", "coord"),
    "deadset_bcast": ("peers",),
    "deadset_recv": ("peers", "src", "kind"),
    "deadset_timeout": ("peers", "coord"),
    # replay mode votes (round = the pool's restart-attempt count)
    "mode_decl": ("pool", "round", "mode", "peers"),
    "mode_vote": ("pool", "round", "mode", "src"),
    "mode_result": ("pool", "round", "mode"),
    # DTD insert-stream skip agreement
    "skip_offer": ("pool", "round", "frontier"),
    "skip_cut": ("pool", "round", "prefix"),
    # minimal-replay need negotiation (round = negotiation round 1..N)
    "need_send": ("pool", "round", "peers"),
    "need_req": ("pool", "src"),
    "need_ack": ("pool", "dst", "ok"),
    "need_round": ("pool", "round", "outcome", "peers"),
    # retirement handshake (incl. the grace-window degradation and the
    # coordinator-succession round that avoids it)
    "retire_report": ("pool", "coord"),
    "retire_recv": ("pool", "src"),
    "retired": ("pool",),
    "retire_degraded": ("pool",),
    "retire_succession": ("pool", "coord"),
    # rejoin incarnation fencing (TAG_REJOIN)
    "rejoin_req": ("src", "epoch", "ok"),
    "rejoin_done": ("epoch",),
    # recovery lifecycle + the chosen replay policy
    "recovery_start": ("peer",),
    "recovery_done": ("peer", "ok"),
    "replay_mode": ("pool", "mode"),
    # barrier generations (comm/engine.py)
    "barrier": ("gen", "outcome"),
    # JobService lifecycle decisions (service/service.py)
    "job_admit": ("job",),
    "job_start": ("job",),
    "job_done": ("job", "status"),
    "job_cancel": ("job",),
    "service_state": ("peer", "state"),
    # serving-fabric decisions (service/fabric.py): admission quotes,
    # placement/release of carved device subsets, elastic resizes,
    # preemption round-trips.  The auditor's F-invariants replay these:
    # exclusive subsets disjoint at all times (F1), exactly one
    # placement outcome per admitted job (F2), every preemption
    # resumed or cancelled (F3).
    "fabric_quote": ("job", "eta"),
    "fabric_admit": ("job", "verdict"),
    "fabric_place": ("job", "devices"),
    "fabric_resize": ("job", "devices", "delta"),
    "fabric_release": ("job",),
    "fabric_preempt": ("job", "by"),
    "fabric_resume": ("job",),
    # predictive health plane (prof/health.py + service/fabric.py):
    # scored state transitions and the drain decisions they justify.
    # The auditor's H1 invariant replays these: every health_drain
    # preceded by recorded below-threshold evidence for the same rank
    # (a transition out of "ok"), and no drained rank placement-
    # targeted while the drain is in force.  ``peer`` not ``rank``:
    # merge_journals stamps ``rank`` (the OBSERVING rank) onto every
    # merged event, so the observed rank must ride another key.
    "health_transition": ("peer", "frm", "to", "score"),
    "health_drain": ("peer", "score", "thr"),
    "health_undrain": ("peer", "score"),
}


def _jsonable(v: Any) -> Any:
    """Normalize emit-site values to wire-safe primitives: peer sets
    become sorted lists, everything exotic becomes its repr."""
    if isinstance(v, (set, frozenset)):
        return sorted(v)
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return repr(v)


class Journal:
    """One per Context.  ``emit`` is the only hot call: a dict build
    plus a bounded ``deque.append`` (atomic under the GIL — the flight
    recorder's ring discipline), stamped with ``perf_counter`` so the
    TAG_CLOCK offsets align per-rank journals exactly like traces.
    Disabled (``journal_enabled=0``) it is a single attribute check.
    """

    def __init__(self, rank: int = 0, cap: Optional[int] = None):
        self.rank = rank
        self.enabled = bool(int(params.get("journal_enabled", 1)))
        n = cap if cap is not None \
            else max(64, int(params.get("journal_ring", 4096)))
        self._ring: deque = deque(maxlen=n)
        self._seq = itertools.count(1)
        #: this rank's incarnation epoch (comm_epoch); re-stamped when
        #: the comm engine attaches — a restarted rank journals under
        #: its bumped incarnation
        self.incarnation = int(params.get("comm_epoch", 0))
        self.nranks = 1
        #: TAG_CLOCK table provider (CommEngine.clock_table) — read at
        #: snapshot/dump time only, never on the emit path
        self._clock_provider: Optional[Callable[[], Dict]] = None
        self._dump_lock = threading.Lock()

    # -- wiring ----------------------------------------------------------
    def attach_comm(self, ce) -> None:
        """Wire the comm engine (RemoteDepEngine construction): the
        journal learns its incarnation and clock table, the engine
        learns where barrier/death events land and how to serve
        cross-rank journal pulls."""
        self.incarnation = int(getattr(ce, "epoch", 0))
        self.nranks = int(getattr(ce, "nranks", 1))
        self._clock_provider = getattr(ce, "clock_table", None)
        ce.journal = self
        ce.journal_provider = self.snapshot

    # -- the emit path ---------------------------------------------------
    def emit(self, etype: str, **fields) -> None:
        """Append one control-plane event.  Call sites pass the
        schema'd fields (EVENT_SCHEMA) as kwargs; sets are normalized
        to sorted lists so snapshots serialize.  Never raises and
        never blocks — a journal failure must not perturb the protocol
        it records."""
        if not self.enabled:
            return
        ev = {"e": etype, "t": time.perf_counter(),
              "seq": next(self._seq), "inc": self.incarnation}
        for k, v in fields.items():
            ev[k] = _jsonable(v)
        self._ring.append(ev)

    # -- read side -------------------------------------------------------
    def tail(self, n: int = 20) -> List[dict]:
        events = list(self._ring)           # one consistent snapshot
        return events[-n:]

    def snapshot(self) -> dict:
        """Wire/merge form: header (rank, incarnation, clock table,
        wall + perf anchors) plus the ring contents.  The perf/wall
        anchor pair lets offline readers print wall-clock times; the
        clock table is what the auditor aligns with."""
        clock = {}
        prov = self._clock_provider
        if prov is not None:
            try:
                clock = {int(r): {"offset": float(st.get("offset", 0.0)),
                                  "rtt": float(st.get("rtt", 0.0))}
                         for r, st in prov().items()}
            except Exception:   # a torn comm engine must not kill reads
                clock = {}
        return {"rank": self.rank, "inc": self.incarnation,
                "nranks": self.nranks, "wall": time.time(),
                "perf": time.perf_counter(), "clock": clock,
                "events": list(self._ring)}

    def dump(self, dir_path: str) -> str:
        """APPEND this journal to ``<dir>/journal-rank<N>.jsonl``: one
        ``{"h": header}`` line then one line per event.  Append (not
        truncate) so a restarted incarnation's dump lands after its
        predecessor's in the same file — the auditor checks epoch
        monotonicity across exactly that boundary."""
        os.makedirs(dir_path, exist_ok=True)
        path = os.path.join(dir_path, f"journal-rank{self.rank}.jsonl")
        snap = self.snapshot()
        events = snap.pop("events")
        with self._dump_lock:
            with open(path, "a") as fh:
                fh.write(json.dumps({"h": snap}) + "\n")
                for ev in events:
                    fh.write(json.dumps(ev) + "\n")
        return path

    def __len__(self) -> int:
        return len(self._ring)


def install_journal(context) -> Journal:
    j = Journal(rank=context.rank)
    context.journal = j
    return j


# ---------------------------------------------------------------------------
# merge + alignment (shared by the auditor, the autopsy tail, and the
# job-port pull)
# ---------------------------------------------------------------------------

def merge_journals(per_rank: Dict[int, dict],
                   ref: Optional[int] = None) -> List[dict]:
    """Fold per-rank snapshots into ONE time-ordered event list on the
    reference (lowest-rank by default) clock.

    Alignment follows prof/critpath.merge_traces: for rank r, prefer
    r's OWN measured offset to the reference (``offset = clock_ref -
    clock_r`` -> ``t + offset``), fall back to the reference's
    measurement of r (negated); same-host journals share
    CLOCK_MONOTONIC so a missing table degrades to zero shift.  Each
    merged event gains ``rank`` and its aligned ``t``."""
    if not per_rank:
        return []
    ranks = sorted(per_rank)
    if ref is None or ref not in per_rank:
        ref = ranks[0]
    ref_clock = (per_rank[ref] or {}).get("clock") or {}
    out: List[dict] = []
    for r in ranks:
        snap = per_rank[r] or {}
        shift = 0.0
        if r != ref:
            own = snap.get("clock") or {}
            ent = own.get(ref, own.get(str(ref)))
            if ent is not None:
                shift = float(ent.get("offset", 0.0))
            else:
                ent = ref_clock.get(r, ref_clock.get(str(r)))
                if ent is not None:
                    shift = -float(ent.get("offset", 0.0))
        for ev in snap.get("events", ()):
            mev = dict(ev)
            mev["rank"] = r
            mev["t"] = float(ev.get("t", 0.0)) + shift
            out.append(mev)
    out.sort(key=lambda e: (e["t"], e["rank"], e.get("seq", 0)))
    return out


def format_event(ev: dict, t0: float = 0.0) -> str:
    """One human-readable timeline line (shared by the autopsy tail
    and ``journal_audit --timeline``)."""
    skip = {"e", "t", "seq", "inc", "rank"}
    extra = " ".join(f"{k}={ev[k]}" for k in ev if k not in skip)
    return (f"t+{ev.get('t', 0.0) - t0:10.4f}s rank {ev.get('rank', '?')}"
            f" inc={ev.get('inc', 0)} {ev.get('e', '?'):16s} {extra}")


def cluster_journals(context, timeout: float = 2.0) -> Dict[int, dict]:
    """This rank's snapshot plus every live peer's, pulled over the
    TAG_METRICS control lane (the job-port ``{"op": "journal"}``
    surface and the autopsy tail both read this).  Degrades to the
    local view, never fails."""
    j = getattr(context, "journal", None)
    local = j.snapshot() if j is not None else {}
    per_rank = {context.rank: local}
    comm = getattr(context, "comm", None)
    ce = getattr(comm, "ce", None) if comm is not None else None
    if ce is not None and context.nranks > 1:
        try:
            per_rank.update(ce.gather_journals(timeout=timeout))
        except Exception:
            pass
    return per_rank
