"""Socket front end for the job service: external processes submit jobs.

A small TCP server in front of a JobService so a resident runtime can be
fed from other processes (tools/job_client.py is the CLI).  The wire
reuses the framing discipline of comm/engine.py — a fixed struct header
carrying magic + protocol version + payload length, rejected on
mismatch — with JSON payloads (requests are control-plane sized, not
tile data):

    !4sII header: (b"PTJS", version, length) then <length> bytes of JSON

Requests are one JSON object; every request gets one JSON reply with an
``ok`` flag.  Ops:

    {"op": "submit", "app": "gemm", "params": {...}, "priority": 5,
     "deadline": 30.0, "client": "cli"}      -> {"ok": true, "job": 7}
                      (under a ServingFabric front the request may also
                       carry "slo", "devices", "devices_max",
                       "resumable" and "slo_policy"; the reply then
                       adds the admission quote: "quote_eta",
                       "verdict" — see service/fabric.py)
    {"op": "status", "job": 7}               -> {"ok": true, "info": {...},
                                                 "queue_position": n|null}
    {"op": "status"}                         -> {"ok": true, "status": {...}}
                      (the LIVE surface: per-job progress, online
                       exec/queue/comm/idle split, stragglers, dagsim
                       ETA — prof/liveattr.py, cross-rank aggregated)
    {"op": "result", "job": 7, "timeout": 60}-> {"ok": true, "result": {...}}
    {"op": "cancel", "job": 7}               -> {"ok": true, "cancelled": b}
    {"op": "jobs"} / {"op": "stats"} / {"op": "gauges"} / {"op": "apps"}
    {"op": "metrics"}  -> {"ok": true, "text": <Prometheus exposition>,
                           "ranks": [...]}   (cross-rank via TAG_METRICS)
    {"op": "journal"}  -> {"ok": true, "ranks": {rank: journal snapshot}}
                      (the control-plane black box, cross-rank — audit
                       with tools/journal_audit.py)

The same port also answers plain HTTP ``GET /metrics`` (Prometheus
text) and ``GET /status`` (the live job-status JSON) — the first four
bytes disambiguate: framed requests lead with the PTJS magic — so a
stock Prometheus scraper or curl needs no client library.

Named apps (the multi-tenant demo catalog) build small self-contained
problems from JSON params and return JSON-able result summaries — the
server never ships tiles over this socket.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from parsec_tpu.service.fabric import ServingFabric
from parsec_tpu.service.job import AdmissionError, JobError
from parsec_tpu.service.service import JobService
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import warning

_HDR = struct.Struct("!4sII")      # (magic, proto version, payload bytes)
_MAGIC = b"PTJS"
_VERSION = 1
_MAX_PAYLOAD = 1 << 20             # control plane: 1 MiB is already huge

params.register("service_port", 41990, "job-server default TCP port")


# ---------------------------------------------------------------------------
# framing (shared by server and client)
# ---------------------------------------------------------------------------

def send_msg(conn: socket.socket, obj: Dict[str, Any]) -> None:
    payload = json.dumps(obj).encode()
    conn.sendall(_HDR.pack(_MAGIC, _VERSION, len(payload)) + payload)


def recv_msg(conn: socket.socket,
             pre: bytes = b"") -> Optional[Dict[str, Any]]:
    """Read one framed request.  ``pre`` is bytes the caller already
    consumed while sniffing the protocol (the HTTP-vs-framed dispatch
    in JobServer._serve_conn)."""
    rest = _recv_exact(conn, _HDR.size - len(pre))
    hdr = pre + rest if rest is not None else None
    if hdr is None:
        return None
    magic, ver, n = _HDR.unpack(hdr)
    if magic != _MAGIC or ver != _VERSION or n > _MAX_PAYLOAD:
        raise ConnectionError(
            f"bad job-wire header (magic={magic!r} version={ver} len={n})")
    payload = _recv_exact(conn, n)
    if payload is None:
        return None
    return json.loads(payload)


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = conn.recv(n - len(buf))
        except socket.timeout:
            # distinguish "server is slow" from "server closed" for
            # clients with a socket timeout (request()); the server's
            # own sockets are blocking and never hit this
            raise TimeoutError("job-server reply timed out")
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


# ---------------------------------------------------------------------------
# named app catalog
# ---------------------------------------------------------------------------

def _gemm_factory(p: Dict[str, Any]) -> Callable:
    n = int(p.get("n", 256))
    nb = int(p.get("nb", 64))
    seed = int(p.get("seed", 0))
    device = str(p.get("device", "cpu"))

    def factory():
        from parsec_tpu.apps.gemm import gemm_taskpool
        from parsec_tpu.data.matrix import TwoDimBlockCyclic
        rng = np.random.default_rng(seed)
        a = rng.standard_normal((n, n)).astype(np.float32)
        b = rng.standard_normal((n, n)).astype(np.float32)
        A = TwoDimBlockCyclic(mb=nb, nb=nb, lm=n, ln=n).from_array(a)
        B = TwoDimBlockCyclic(mb=nb, nb=nb, lm=n, ln=n).from_array(b)
        C = TwoDimBlockCyclic(mb=nb, nb=nb, lm=n, ln=n).from_array(
            np.zeros((n, n), np.float32))
        tp = gemm_taskpool(A, B, C, beta=0.0, device=device)

        def result():
            out = C.to_array()
            return {"app": "gemm", "n": n,
                    "fro_norm": float(np.linalg.norm(out))}
        return tp, result
    return factory


def _potrf_factory(p: Dict[str, Any]) -> Callable:
    n = int(p.get("n", 128))
    nb = int(p.get("nb", 32))
    seed = int(p.get("seed", 0))
    device = str(p.get("device", "cpu"))

    def factory():
        from parsec_tpu.apps.potrf import potrf_taskpool
        from parsec_tpu.data.matrix import TwoDimBlockCyclic
        rng = np.random.default_rng(seed)
        b = rng.standard_normal((n, n)).astype(np.float32)
        spd = (b @ b.T + n * np.eye(n)).astype(np.float32)
        A = TwoDimBlockCyclic(mb=nb, nb=nb, lm=n, ln=n).from_array(
            spd.copy())
        tp = potrf_taskpool(A, device=device)

        def result():
            L = np.tril(A.to_array())
            err = float(np.abs(L @ L.T - spd).max()
                        / np.abs(spd).max())
            return {"app": "potrf", "n": n, "residual": err}
        return tp, result
    return factory


def _stencil_factory(p: Dict[str, Any]) -> Callable:
    n = int(p.get("n", 256))
    nb = int(p.get("nb", 64))
    steps = int(p.get("steps", 8))
    seed = int(p.get("seed", 0))
    device = str(p.get("device", "cpu"))

    def factory():
        from parsec_tpu.apps.stencil import stencil_taskpool
        from parsec_tpu.data.matrix import VectorTwoDimCyclic
        rng = np.random.default_rng(seed)
        x = rng.standard_normal(n).astype(np.float32)
        V = VectorTwoDimCyclic(mb=nb, lm=n).from_array(x)
        tp = stencil_taskpool(V, steps, device=device)

        def result():
            return {"app": "stencil", "n": n, "steps": steps,
                    "norm": float(np.linalg.norm(V.to_array()))}
        return tp, result
    return factory


#: name -> params-dict -> taskpool factory
APPS: Dict[str, Callable[[Dict[str, Any]], Callable]] = {
    "gemm": _gemm_factory,
    "potrf": _potrf_factory,
    "stencil": _stencil_factory,
}


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class JobServer:
    """TCP front end over a JobService; one handler thread per client
    connection, requests served sequentially per connection."""

    def __init__(self, service: JobService, host: str = "127.0.0.1",
                 port: Optional[int] = None):
        self.service = service
        self._stop = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port if port is not None
                        else int(params.get("service_port", 41990))))
        self._srv.listen(16)
        self.host, self.port = self._srv.getsockname()[:2]
        # selector-driven accept (comm/engine.py event-loop discipline):
        # a nonblocking listener + a self-pipe instead of a 0.2s accept
        # timeout poll — close() interrupts the wait instantly and an
        # idle server makes zero wakeups
        self._srv.setblocking(False)
        self._wake_r, self._wake_w = socket.socketpair()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="job-server", daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        import selectors
        sel = selectors.DefaultSelector()
        sel.register(self._srv, selectors.EVENT_READ, "accept")
        sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        try:
            while not self._stop:
                try:
                    events = sel.select()
                except OSError:
                    return       # close() raced us and closed the fds
                for key, _mask in events:
                    if key.data != "accept":
                        return                       # close() poked us
                    try:
                        conn, _addr = self._srv.accept()
                    except (BlockingIOError, InterruptedError):
                        continue
                    except OSError:
                        return
                    # accepted sockets may inherit the listener's
                    # nonblocking mode (BSD/macOS): the per-connection
                    # handler's framed recv is written blocking
                    conn.setblocking(True)
                    # request/reply latency discipline of the comm
                    # transport: no Nagle stall on small JSON replies
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    threading.Thread(target=self._serve_conn,
                                     args=(conn,), daemon=True).start()
        finally:
            sel.close()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop:
                # protocol sniff: the framed wire always leads with the
                # PTJS magic, so a plain-HTTP scraper (Prometheus, curl)
                # is recognizable from its first four bytes and served
                # a one-shot text exposition on the SAME port
                try:
                    head = _recv_exact(conn, 4)
                except OSError:
                    return
                if head is None:
                    return
                if head in (b"GET ", b"HEAD"):
                    self._serve_http(conn, head)
                    return
                try:
                    req = recv_msg(conn, pre=head)
                except (ConnectionError, ValueError) as exc:
                    warning("job-server: dropping connection: %s", exc)
                    return
                if req is None:
                    return
                try:
                    reply = self._handle(req)
                except Exception as exc:   # a bad request must not kill
                    reply = {"ok": False,  # the handler thread
                             "error": f"{type(exc).__name__}: {exc}"}
                try:
                    send_msg(conn, reply)
                except OSError:
                    return

    def _serve_http(self, conn: socket.socket, head: bytes) -> None:
        """One-shot HTTP scrape: ``GET /metrics`` answers the Prometheus
        text exposition (cross-rank aggregated); anything else 404s.
        The request head is drained (bounded) so pipelined headers do
        not linger in the kernel buffer past the close; a stalled
        scraper (slow-loris) trips the socket timeout instead of
        pinning this connection thread forever — this path invites
        arbitrary external HTTP clients onto the port."""
        try:
            conn.settimeout(10.0)
        except OSError:
            return
        data = head
        while b"\r\n\r\n" not in data and len(data) < 8192:
            try:
                chunk = conn.recv(1024)
            except OSError:
                return
            if not chunk:
                break
            data += chunk
        line = data.split(b"\r\n", 1)[0].decode("latin-1", "replace")
        parts = line.split()
        path = (parts[1] if len(parts) > 1 else "/").split("?", 1)[0]
        ctype = "text/plain; version=0.0.4; charset=utf-8"
        if path.rstrip("/") == "/metrics" or path == "/":
            from parsec_tpu.prof.metrics import cluster_exposition
            try:
                text, _ranks = cluster_exposition(self.service.context)
            except Exception as exc:   # scrape must answer, not hang up
                text = f"# scrape failed: {exc}\n"
            status, body = "200 OK", text.encode()
        elif path.rstrip("/") == "/status":
            # the live job-status document (same payload as the framed
            # job-less {"op": "status"}), as JSON for curl/dashboards
            from parsec_tpu.prof.liveattr import cluster_status
            try:
                doc = cluster_status(self.service.context, self.service)
                status, body = "200 OK", json.dumps(doc).encode()
            except Exception as exc:
                status = "500 Internal Server Error"
                body = json.dumps(
                    {"error": f"{type(exc).__name__}: {exc}"}).encode()
            ctype = "application/json"
        else:
            status = "404 Not Found"
            body = (b"parsec_tpu job server: scrape GET /metrics or "
                    b"GET /status\n")
        hdrs = (f"HTTP/1.0 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                "Connection: close\r\n\r\n")
        try:
            conn.sendall(hdrs.encode()
                         + (b"" if head == b"HEAD" else body))
        except OSError:
            pass

    # -- request handling --------------------------------------------------
    def _job_of(self, req: Dict[str, Any]):
        job = self.service.job(int(req["job"]))
        if job is None:
            raise KeyError(f"no such job {req.get('job')!r}")
        return job

    def _handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        if op == "submit":
            return self._op_submit(req)
        if op == "status":
            if req.get("job") is not None:
                # per-job record (the original op shape); under a
                # ServingFabric front a PENDING job also learns its
                # 0-based dispatch-order position in the queue
                job = self._job_of(req)
                reply = {"ok": True, "info": job.info()}
                qp = getattr(self.service, "queue_position", None)
                if callable(qp):
                    reply["queue_position"] = qp(job.job_id)
                return reply
            # job-less status: the LIVE streaming surface — per-job DAG
            # progress, the online exec/queue/comm/idle split, straggler
            # list and the dagsim ETA, aggregated cross-rank over the
            # same TAG_METRICS pull as /metrics (prof/liveattr.py)
            from parsec_tpu.prof.liveattr import cluster_status
            doc = cluster_status(
                self.service.context, self.service,
                aggregate=bool(req.get("aggregate", True)),
                timeout=float(req.get("timeout", 2.0)))
            return {"ok": True, "status": doc}
        if op == "result":
            job = self._job_of(req)
            try:
                res = job.result(timeout=req.get("timeout", 60.0))
            except JobError as exc:
                return {"ok": False, "status": job.status().name,
                        "error": str(exc)}
            return {"ok": True, "status": job.status().name,
                    "result": res}
        if op == "cancel":
            job = self._job_of(req)
            return {"ok": True, "cancelled": job.cancel()}
        if op == "jobs":
            return {"ok": True,
                    "jobs": [j.info() for j in self.service.jobs()]}
        if op == "stats":
            return {"ok": True, "stats": self.service.stats()}
        if op == "gauges":
            return {"ok": True, "gauges": self.service.gauges.snapshot()}
        if op == "metrics":
            from parsec_tpu.prof.metrics import cluster_exposition
            text, ranks = cluster_exposition(
                self.service.context,
                aggregate=bool(req.get("aggregate", True)),
                timeout=float(req.get("timeout", 2.0)))
            return {"ok": True, "text": text, "ranks": ranks}
        if op == "journal":
            # the control-plane black box: every rank's protocol
            # journal (recovery rounds, retirement handshakes, rejoin
            # fencing, barrier generations, job lifecycle), pulled
            # cross-rank over the TAG_METRICS control lane — feed the
            # result to tools/journal_audit.py --timeline / --audit
            from parsec_tpu.prof.journal import cluster_journals
            per_rank = cluster_journals(
                self.service.context,
                timeout=float(req.get("timeout", 2.0)))
            return {"ok": True,
                    "ranks": {str(r): snap
                              for r, snap in per_rank.items()}}
        if op == "apps":
            return {"ok": True, "apps": sorted(APPS)}
        raise ValueError(f"unknown op {op!r}")

    def _op_submit(self, req: Dict[str, Any]) -> Dict[str, Any]:
        app = req.get("app")
        maker = APPS.get(app)
        if maker is None:
            raise ValueError(f"unknown app {app!r} (have {sorted(APPS)})")
        factory = maker(dict(req.get("params") or {}))
        # coerce numeric wire fields: a string deadline from a sloppy
        # client must fail THIS request, not poison the deadline sweep
        deadline = req.get("deadline")
        timeout = req.get("timeout")
        kw: Dict[str, Any] = dict(
            priority=int(req.get("priority", 0)),
            deadline=None if deadline is None else float(deadline),
            client=str(req.get("client", "")),
            name=str(req.get("name", "") or f"{app}"),
            block=bool(req.get("block", False)),
            timeout=None if timeout is None else float(timeout))
        if isinstance(self.service, ServingFabric):
            # fabric-only admission fields; a plain JobService front
            # silently ignores them (its submit has no such kwargs)
            slo = req.get("slo")
            devices = req.get("devices")
            kw.update(
                slo=None if slo is None else float(slo),
                devices=None if devices is None else int(devices),
                devices_max=int(req.get("devices_max", 0) or 0),
                resumable=bool(req.get("resumable", False)),
                app=str(app),
                slo_policy=str(req.get("slo_policy", "") or ""))
        try:
            job = self.service.submit(factory, **kw)
        except AdmissionError as exc:
            return {"ok": False, "rejected": True, "error": str(exc)}
        reply = {"ok": True, "job": job.job_id, "name": job.name}
        if getattr(job, "verdict", None) is not None:
            reply["quote_eta"] = job.quote_eta
            reply["verdict"] = job.verdict
        return reply

    def close(self) -> None:
        self._stop = True
        try:
            self._wake_w.send(b"\0")     # interrupt the selector wait
        except OSError:
            pass
        self._thread.join(timeout=2)
        for s in (self._srv, self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# client library (used by tools/job_client.py and tests)
# ---------------------------------------------------------------------------

def request(host: str, port: int, obj: Dict[str, Any],
            timeout: float = 120.0) -> Dict[str, Any]:
    """One request/reply round trip on a fresh connection."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        send_msg(s, obj)
        reply = recv_msg(s)
    if reply is None:
        raise ConnectionError("job server closed the connection")
    return reply


def serve(port: Optional[int] = None, host: str = "127.0.0.1",
          fabric: bool = False,
          **service_kwargs) -> Tuple[JobService, JobServer]:
    """Bring up a resident service + server pair (blocking callers use
    ``serve_forever``).  ``fabric=True`` fronts a ServingFabric —
    mesh carving, SLO quotes, preemption — instead of the plain
    temporally-shared JobService."""
    cls = ServingFabric if fabric else JobService
    service = cls(**service_kwargs)
    server = JobServer(service, host=host, port=port)
    return service, server


def serve_forever(port: Optional[int] = None, host: str = "127.0.0.1",
                  **service_kwargs) -> None:
    import time as _time
    service, server = serve(port=port, host=host, **service_kwargs)
    print(f"parsec_tpu job server on {server.host}:{server.port} "
          f"(apps: {', '.join(sorted(APPS))})", flush=True)
    try:
        while True:
            _time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        service.shutdown(timeout=30.0)


def main(argv=None) -> None:
    import argparse
    ap = argparse.ArgumentParser(
        description="resident parsec_tpu job server")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--cores", type=int, default=None,
                    help="worker streams for the warm context")
    ap.add_argument("--fabric", action="store_true",
                    help="front a ServingFabric (mesh carving, SLO "
                         "quotes, preemption) instead of the plain "
                         "temporally-shared JobService")
    args, rest = ap.parse_known_args(argv)
    if rest:
        params.parse_cmdline(rest)
    kw = {}
    if args.cores is not None:
        kw["nb_cores"] = args.cores
    serve_forever(port=args.port, host=args.host, fabric=args.fabric,
                  **kw)


if __name__ == "__main__":
    main()
