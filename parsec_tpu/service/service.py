"""JobService: a resident, multi-tenant front door for one warm Context.

The batch shape of the runtime (build Context -> add_taskpool ->
Context.wait -> fini) keeps nothing warm between runs.  The job service
inverts it: ONE long-lived Context (worker streams, devices, comm
threads stay up) accepts a stream of independent jobs and multiplexes
them onto the same scheduler — the PaRSEC capability of multiple
simultaneously-enqueued DAGs on one context (PAPER.md; reference:
parsec_context_add_taskpool is explicitly many-pools-per-context,
scheduling.c:678), grown into a serving front end.

Pieces:

  admission   — a bounded pending queue (``service_max_pending``) and a
                cap on concurrently-attached taskpools
                (``service_max_active``); a full queue rejects
                (AdmissionError) or exerts backpressure
                (``submit(block=True, timeout=...)``).
  fairness    — per-job priority lands on Taskpool.priority, which every
                Task adds to its class priority (core/task.py), so the
                priority schedulers (pbq/ltq/lhq/llp) interleave
                concurrent jobs by weight; the service queue itself
                dispatches by aged priority (``service_aging_weight``
                per second of wait) so low-priority jobs cannot starve.
  lifecycle   — cancel() drops undelivered tasks and force-quiesces the
                pool's termdet (core/taskpool.cancel); deadlines cancel
                the job, never the context; drain()/shutdown() finish
                gracefully.
  isolation   — each job pool carries an error_sink, so one job's
                failure stays on its handle (Context.record_error
                routes it) and the context keeps serving other jobs.
  observability — JobGauges (prof/gauges.py) publishes per-job counters
                through the aggregator path; job lifecycle emits
                job_submit/job_start/job_done PINS events, and tasks
                are attributable via Taskpool.job_id.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from parsec_tpu.core.errors import PeerFailedError
from parsec_tpu.core.taskpool import Compound, Taskpool
from parsec_tpu.prof.gauges import JobGauges
from parsec_tpu.service.job import (AdmissionError, JobHandle, JobStatus)
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose

params.register("service_max_active", 4,
                "max taskpools concurrently attached to the context")
params.register("service_max_pending", 64,
                "bounded pending-job queue depth before rejection")
params.register("service_priority_scale", 1024,
                "job priority -> task priority multiplier (keeps job "
                "weight above app-internal task priorities)")
params.register("service_aging_weight", 1.0,
                "pending-queue aging: priority points gained per second "
                "of wait (starvation guard; 0 disables)")
params.register("service_poll_interval", 0.02,
                "dispatcher tick in seconds (deadline sweep granularity)")
params.register("service_history_limit", 512,
                "finished jobs kept in the service index (handles held "
                "by callers stay valid after eviction)")


class JobService:
    """Resident job service owning (or wrapping) one warm Context."""

    def __init__(self, context=None, *,
                 max_active: Optional[int] = None,
                 max_pending: Optional[int] = None,
                 aging_weight: Optional[float] = None,
                 **context_kwargs):
        if context is None:
            from parsec_tpu.core.context import Context
            context = Context(**context_kwargs)
            self._own_context = True
        else:
            if context_kwargs:
                raise ValueError("context kwargs need context=None")
            self._own_context = False
        self.context = context
        self._max_active = int(max_active if max_active is not None
                               else params.get("service_max_active", 4))
        self._max_pending = int(max_pending if max_pending is not None
                                else params.get("service_max_pending", 64))
        self._prio_scale = int(params.get("service_priority_scale", 1024))
        self._aging = float(aging_weight if aging_weight is not None
                            else params.get("service_aging_weight", 1.0))
        self._tick = float(params.get("service_poll_interval", 0.02))
        self._history = int(params.get("service_history_limit", 512))
        self._seq = itertools.count(1)
        self._lock = threading.RLock()
        self._space = threading.Condition(self._lock)   # admission room
        self._work = threading.Condition(self._lock)    # dispatcher wakeup
        self._pending: List[JobHandle] = []
        self._running: Dict[int, JobHandle] = {}
        self._jobs: Dict[int, JobHandle] = {}   # insertion-ordered history
        self._draining = False
        self._stop = False
        #: DEGRADED MODE: ranks declared dead while the service runs.
        #: Jobs whose taskpools touched a dead rank were failed by the
        #: containment route (PeerFailedError -> error_sink -> _job_error)
        #: — the service keeps admitting and serving every job that stays
        #: off the dead ranks (single-rank pools, jobs on surviving
        #: ranks); the set is surfaced through stats()/degraded for
        #: operators and load balancers
        self._degraded_ranks: set = set()
        #: ranks a recovery is currently re-executing around (the
        #: degraded -> recovering -> healthy transition the stats
        #: surface and tools/job_client.py expose); guarded-by: _lock
        self._recovering_ranks: set = set()
        self.gauges = JobGauges(self)
        self.gauges.install(context)
        if getattr(context, "recovery", None) is not None:
            # the recovery plane reports start/done/rejoin transitions
            # so degraded-mode bookkeeping UN-degrades (pre-recovery
            # these sets were set-only — a healed service looked sick
            # forever)
            context.recovery.attach_service(self)
        # the always-on metrics registry (prof/metrics.py) folds the
        # service view into its scrape: job queue depths, degraded
        # flag, per-job task counters over the JobGauges window, and
        # the admission->completion SLO histograms fed by the job_*
        # PINS events this service already emits
        if getattr(context, "metrics", None) is not None:
            context.metrics.attach_service(self)
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="job-service", daemon=True)
        self._thread.start()

    # -- submission --------------------------------------------------------
    def submit(self, factory: Callable, *, priority: int = 0,
               deadline: Optional[float] = None, client: str = "",
               name: str = "", block: bool = False,
               timeout: Optional[float] = None) -> JobHandle:
        """Admit a job.  ``factory()`` runs at dispatch time and returns
        a taskpool or ``(taskpool, result_fn)``.  ``deadline`` is a
        wall-clock budget in seconds from submission; on expiry the job
        is cancelled (status TIMEOUT) and the context lives on.

        A full pending queue raises AdmissionError immediately, or —
        with ``block=True`` — blocks up to ``timeout`` seconds for room
        (backpressure) before raising."""
        deadline = None if deadline is None else float(deadline)
        wait_deadline = (None if timeout is None
                         else time.monotonic() + timeout)
        with self._lock:
            while True:
                if self._draining or self._stop:
                    raise AdmissionError("service is draining")
                if len(self._pending) < self._max_pending:
                    break
                if not block:
                    raise AdmissionError(
                        f"pending queue full ({self._max_pending})")
                remaining = (None if wait_deadline is None
                             else wait_deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise AdmissionError(
                        f"pending queue still full ({self._max_pending}) "
                        f"after {timeout}s backpressure wait")
                self._space.wait(remaining)
            job = JobHandle(next(self._seq), factory, priority=priority,
                            deadline=deadline, client=client, name=name,
                            service=self)
            self._pending.append(job)
            self._jobs[job.job_id] = job
            self._work.notify_all()
        self._emit("job_submit", job)
        jr = getattr(self.context, "journal", None)
        if jr is not None:
            # admission decisions are control-plane history: the black
            # box records every admit/dispatch/terminal transition next
            # to the recovery rounds that may explain their latency
            jr.emit("job_admit", job=job.job_id, priority=priority,
                    name=job.name)
        debug_verbose(3, "service: admitted %s prio=%d", job.name, priority)
        return job

    # -- dispatcher --------------------------------------------------------
    def _score(self, job: JobHandle, now_mono: float) -> tuple:
        aged = job.priority + self._aging * (now_mono - job.submitted_mono)
        return (aged, -job.job_id)          # ties: oldest first

    def _dispatch_loop(self) -> None:
        while True:
            try:
                if self._dispatch_once():
                    return
            except Exception as exc:
                # the dispatcher is the service's heartbeat: an escaped
                # exception (bad job field, broken factory interplay)
                # must not silently kill dispatch forever
                debug_verbose(1, "service dispatcher: %r", exc)
                time.sleep(self._tick)

    def _dispatch_once(self) -> bool:
        """One dispatcher iteration; returns True to exit the loop."""
        job = None
        with self._lock:
            if self._stop:
                # leftover pending jobs (drain timed out / forced stop)
                # must not dangle forever
                for j in self._pending:
                    if j._to(JobStatus.CANCELLED):
                        self._emit_done(j)
                self._pending.clear()
                self._space.notify_all()
                return True
            now = time.monotonic()
            self._sweep_deadlines(now)
            job = self._pick_job(now)
            if job is not None:
                self._pending.remove(job)
                self._running[job.job_id] = job
                self._space.notify_all()
            else:
                self._work.wait(self._tick)
        if job is not None:
            # launch off-thread: a slow factory (tile allocation at
            # dispatch time) must not head-of-line-block further
            # dispatch or the deadline sweep
            threading.Thread(target=self._launch, args=(job,),
                             name=f"job-launch-{job.job_id}",
                             daemon=True).start()
        return False

    def _pick_job(self, now_mono: float) -> Optional[JobHandle]:
        """Select the next pending job to dispatch (lock held); None
        keeps the dispatcher waiting.  The base policy is aged-priority
        order under the active cap; the serving fabric overrides this
        with placement-aware admission (service/fabric.py)."""
        if self._pending and len(self._running) < self._max_active:
            return max(self._pending,
                       key=lambda j: self._score(j, now_mono))
        return None

    def _sweep_deadlines(self, now_mono: float) -> None:
        """Expire deadlines (lock held; monotonic clock).  Pool
        cancellation is safe here: termdet callbacks never run while
        holding the termdet lock, and our lock is reentrant for
        same-thread completion callbacks."""
        for job in list(self._pending):
            if job.deadline is not None \
                    and now_mono - job.submitted_mono > job.deadline:
                self._pending.remove(job)
                if job._to(JobStatus.TIMEOUT):
                    self._emit_done(job)
                self._space.notify_all()
        for job in list(self._running.values()):
            if job.deadline is not None \
                    and now_mono - job.submitted_mono > job.deadline:
                if job._to(JobStatus.TIMEOUT) and job.taskpool is not None:
                    job.taskpool.cancel()

    def _launch(self, job: JobHandle) -> None:
        try:
            made = job.factory()
            if not getattr(job, "resumable", False):
                job.factory = None  # one-shot; drop the closure early
                # (a resumable job keeps its factory: a fabric
                # preemption re-queues it and re-runs the factory)
            tp, result_fn = (made if isinstance(made, tuple) else (made,
                                                                   None))
            job._result_fn = result_fn
            self._brand(tp, job)
            job.taskpool = tp
            if not job._to(JobStatus.RUNNING):
                # cancelled / timed out while the factory ran: the pool
                # was never attached; close the job out here (nothing
                # else will emit its job_done)
                tp.cancel()
                with self._lock:
                    self._running.pop(job.job_id, None)
                    self._release_job(job)
                    self._prune_history()
                    self._work.notify_all()
                self._emit_done(job)
                return
            job.started_at = time.time()
            tp.on_complete(lambda _tp, job=job: self._finish(job))
            self._emit("job_start", job)
            jr = getattr(self.context, "journal", None)
            if jr is not None:
                jr.emit("job_start", job=job.job_id,
                        pool=tp.taskpool_id)
            self.context.add_taskpool(tp, start=True)
            if tp.cancelled and not tp.completed:
                # cancel()/deadline fired between _to(RUNNING) and the
                # attach: its cancel saw a CREATED pool and could not
                # quiesce the termdet — re-cancel now that it is
                # attached (same post-attach re-check as Compound._drive)
                tp.cancel()
        except Exception as exc:
            job._exc = exc
            job._to(JobStatus.FAILED)
            with self._lock:
                self._running.pop(job.job_id, None)
                self._release_job(job)
                self._work.notify_all()
            self._emit_done(job)

    def _brand(self, tp: Taskpool, job: JobHandle) -> None:
        """Stamp a job's pool tree: id tag (PINS/gauges attribution),
        priority bias (fairness), and the per-pool error route
        (isolation)."""
        tp.job_id = job.job_id
        tp.priority = job.priority * self._prio_scale
        tp.error_sink = lambda exc, task, job=job: \
            self._job_error(job, exc, task)
        if isinstance(tp, Compound):
            for sub in tp.pools:
                self._brand(sub, job)

    # -- completion / failure ---------------------------------------------
    def _finish(self, job: JobHandle) -> None:
        """Pool termination callback (worker thread).

        A completed pool restarted by the recovery plane (a peer died
        inside its restartable window) TERMINATES A SECOND TIME when
        the replay drains — the re-fired completion is absorbed here,
        below the service seam: the job's terminal transition already
        happened and its one ``job_done`` already emitted (SLO
        histograms, gauges, and client waiters must each see exactly
        one terminal event per job)."""
        if job._done_emitted:
            debug_verbose(2, "service: %s re-completed after a "
                          "recovery restart; absorbed", job.name)
            return
        job._to(JobStatus.DONE)     # keeps FAILED/CANCELLED/TIMEOUT
        if job.status() != JobStatus.DONE:
            # no result will ever be read: drop the result closure (it
            # captures the job's tile collections) right away
            job._result_fn = None
        if self.context.comm is None and job.taskpool is not None:
            # the context registry keeps pools for late remote GETs
            # (Context.taskpools); a single-rank resident service has no
            # remote peers, and keeping every served pool is an O(jobs)
            # leak of tile memory
            self.context.taskpools.pop(job.taskpool.taskpool_id, None)
            if isinstance(job.taskpool, Compound):
                for sub in job.taskpool.pools:
                    self.context.taskpools.pop(sub.taskpool_id, None)
        with self._lock:
            self._running.pop(job.job_id, None)
            self._release_job(job)
            self._prune_history()
            self._work.notify_all()
        self._emit_done(job)

    def _release_job(self, job: JobHandle) -> None:
        """Hook (lock held) fired whenever a job leaves the running
        set, whatever path removed it.  The base service holds no
        placements; the serving fabric overrides this to return the
        job's carved device subset to the free list."""

    def _prune_history(self) -> None:
        """Bound the job index (lock held): a resident service must not
        grow O(jobs served).  Only terminal jobs are evicted — callers'
        handles stay fully usable, they just leave the index/gauges."""
        excess = len(self._jobs) - self._history
        if excess <= 0:
            return
        for jid in [j.job_id for j in self._jobs.values()
                    if j.done][:excess]:
            self._jobs.pop(jid, None)

    def _job_error(self, job: JobHandle, exc: Exception, task) -> None:
        """Per-pool error sink (Context.record_error routes here): fail
        THIS job and drain its pool; the context keeps serving."""
        job._exc = exc
        job._failed_task = task
        if isinstance(exc, PeerFailedError):
            # peer-death containment: the job dies, the SERVICE degrades
            # — record the dead rank so operators see the reduced
            # capacity while unaffected jobs keep running
            job.failed_rank = exc.rank
            with self._lock:
                self._degraded_ranks.add(exc.rank)
            jr = getattr(self.context, "journal", None)
            if jr is not None:
                jr.emit("service_state", peer=exc.rank,
                        state="degraded", cause="containment",
                        job=job.job_id)
        took = job._to(JobStatus.FAILED)
        debug_verbose(2, "service: %s failed on %s: %s", job.name, task,
                      exc)
        if took and job.taskpool is not None:
            job.taskpool.cancel()   # fires _finish via termination

    # -- lifecycle ---------------------------------------------------------
    def cancel(self, job: JobHandle) -> bool:
        with self._lock:
            if job.status() == JobStatus.PENDING:
                in_queue = job in self._pending
                if in_queue:
                    self._pending.remove(job)
                    self._space.notify_all()
                took = job._to(JobStatus.CANCELLED)
                if took:
                    jr = getattr(self.context, "journal", None)
                    if jr is not None:
                        jr.emit("job_cancel", job=job.job_id)
                # a PENDING job not in the queue is in the dispatcher's
                # hands (factory running): _launch's failed RUNNING
                # transition owns the job_done emission there, so only
                # emit for jobs cancelled straight out of the queue
                if took and in_queue:
                    self._emit_done(job)
                return took
            if job.status() != JobStatus.RUNNING:
                return False
            took = job._to(JobStatus.CANCELLED)
            tp = job.taskpool
        if took:
            jr = getattr(self.context, "journal", None)
            if jr is not None:
                jr.emit("job_cancel", job=job.job_id)
        if took and tp is not None:
            tp.cancel()             # termination fires _finish
        return took

    def jobs(self) -> List[JobHandle]:
        """All jobs this service has seen, in submission order."""
        with self._lock:
            return list(self._jobs.values())

    def job(self, job_id: int) -> Optional[JobHandle]:
        with self._lock:
            return self._jobs.get(job_id)

    @property
    def degraded(self) -> bool:
        """True while any peer rank is dead under the service
        (containment kept unaffected jobs running; capacity is
        reduced).  CLEARED when recovery completes or the rank
        rejoins — degraded is a state, not a scar."""
        return bool(self._degraded_ranks)

    def degraded_ranks(self) -> List[int]:
        with self._lock:
            return sorted(self._degraded_ranks)

    def note_recovery(self, event: str, rank: int) -> None:
        """Recovery-plane transitions (core/recovery.py notifier):
        ``start`` marks the rank degraded+recovering, ``done`` heals it
        (re-mapped partition serving again), ``failed`` leaves it
        degraded, ``rejoin`` heals it fully (the rank itself is back).
        Running jobs that were stamped with the failed rank but
        survived through recovery get the stamp cleared; terminally
        FAILED jobs keep theirs — it is their diagnosis."""
        with self._lock:
            if event == "start":
                self._degraded_ranks.add(rank)
                self._recovering_ranks.add(rank)
                jobs = []
                state = "recovering"
            elif event in ("done", "rejoin"):
                self._recovering_ranks.discard(rank)
                self._degraded_ranks.discard(rank)
                jobs = [j for j in self._jobs.values()
                        if j.failed_rank == rank and not j.done]
                state = "healthy"
            else:   # failed: recovery gave up; the degradation stands
                self._recovering_ranks.discard(rank)
                jobs = []
                state = "degraded"
        jr = getattr(self.context, "journal", None)
        if jr is not None:
            jr.emit("service_state", peer=rank, state=state,
                    cause=event)
        for job in jobs:
            job.failed_rank = None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "pending": len(self._pending),
                "running": len(self._running),
                "total": len(self._jobs),
                "max_active": self._max_active,
                "max_pending": self._max_pending,
                "degraded": bool(self._degraded_ranks),
                "degraded_ranks": sorted(self._degraded_ranks),
                "recovering": bool(self._recovering_ranks),
                "recovering_ranks": sorted(self._recovering_ranks),
            }

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting; wait for every admitted job to finish.
        Returns False when ``timeout`` elapsed first (drain stays on)."""
        with self._lock:
            self._draining = True
            jobs = list(self._jobs.values())
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for job in jobs:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not job.wait(remaining):
                return False
        return True

    def shutdown(self, timeout: Optional[float] = None,
                 cancel_jobs: bool = False) -> None:
        """Graceful stop: drain (or cancel everything), stop the
        dispatcher, detach gauges, and fini the context if owned."""
        with self._lock:
            self._draining = True
            jobs = list(self._jobs.values())
        if cancel_jobs:
            for job in jobs:
                self.cancel(job)
        if not self.drain(timeout):
            # drain timed out: force-cancel what's left so the context
            # quiesces before a possible fini (stuck jobs must not keep
            # pools attached through teardown)
            for job in jobs:
                self.cancel(job)
            self.drain(5.0)
        with self._lock:
            self._stop = True
            self._work.notify_all()
            self._space.notify_all()
        self._thread.join(timeout=5)
        self.gauges.uninstall(self.context)
        if getattr(self.context, "metrics", None) is not None:
            self.context.metrics.detach_service(self)
        if getattr(self.context, "recovery", None) is not None:
            self.context.recovery.detach_service(self)
        if self._own_context:
            self.context.fini()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- plumbing ----------------------------------------------------------
    def _sync_devices(self) -> None:
        """Quiesce accelerator pipelines before a job result is read
        (deps release eagerly on dispatch; see Context.wait).  Task
        errors routed through record_error belong to whichever job
        dispatched them — its error_sink already fired — but a SYNC
        failure (timeout, wedged device, stuck chain hold) has no
        error_sink route: the reader's data is not materialized, so it
        must not silently read stale tiles."""
        try:
            self.context.sync_devices(timeout=60.0)
        except Exception as exc:
            debug_verbose(2, "service device sync: %s", exc)
            raise RuntimeError(
                "device sync failed before result read") from exc

    def _emit_done(self, job: JobHandle) -> None:
        """Emit a job's terminal ``job_done`` EXACTLY ONCE, whatever
        path reached it first (completion, failure, cancel, deadline,
        dispatcher stop) and however often a recovery restart re-fires
        the pool's termination afterwards."""
        with job._lock:
            if job._done_emitted:
                return
            job._done_emitted = True
        jr = getattr(self.context, "journal", None)
        if jr is not None:
            jr.emit("job_done", job=job.job_id,
                    status=job.status().name.lower())
        self._emit("job_done", job)

    def _emit(self, event: str, job: JobHandle) -> None:
        """Job-lifecycle PINS events (payload: the JobHandle)."""
        for cb in self.context._pins.get(event, ()):
            try:
                cb(None, event, job)
            except Exception as exc:
                debug_verbose(2, "pins %s callback: %s", event, exc)
