"""Resident job service: multi-tenant concurrent taskpool submission.

One warm Context serves a stream of independent jobs with admission
control, weighted fairness, per-job lifecycle (cancel/deadline), error
isolation, and per-job observability — the serving layer over the
batch runtime (see service/service.py for the design notes, and
service/server.py + tools/job_client.py for the socket front end).
"""

from parsec_tpu.service.job import (AdmissionError, JobCancelled,  # noqa: F401
                                    JobError, JobHandle, JobStatus,
                                    JobTimeout)
from parsec_tpu.service.service import JobService  # noqa: F401
