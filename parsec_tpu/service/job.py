"""Jobs: the unit of submission of the resident job service.

A job wraps a taskpool FACTORY — a zero-argument callable producing the
taskpool (and optionally a result thunk) — plus submission options:
priority, deadline, client id.  The factory runs at DISPATCH time, not
submission time, so queued jobs hold no tile memory while waiting for
an admission slot.

The factory may return either

    taskpool                       -> result() returns None
    (taskpool, result_fn)          -> result() returns result_fn() after
                                      the pool completes

``JobHandle`` is the caller's view: ``result()`` / ``cancel()`` /
``status()`` / ``wait()``, mirroring concurrent.futures discipline but
backed by taskpool termination instead of a thread.
"""

from __future__ import annotations

import threading
import time
from enum import IntEnum
from typing import Any, Callable, Dict, Optional


class JobStatus(IntEnum):
    PENDING = 0     # admitted to the service queue, not yet dispatched
    RUNNING = 1     # taskpool(s) attached to the context
    DONE = 2        # completed normally
    FAILED = 3      # a task raised; error kept job-local
    CANCELLED = 4   # cancel() before completion
    TIMEOUT = 5     # deadline expired; pool cancelled, context kept


class JobError(RuntimeError):
    """A job's task raised; carries the original exception as __cause__."""


class JobCancelled(JobError):
    """result() on a cancelled job."""


class JobTimeout(JobError):
    """result() on a job whose deadline expired."""


class AdmissionError(RuntimeError):
    """Submission rejected: pending queue full (after any backpressure
    wait) or the service is draining/closed."""


#: statuses from which no further transition happens
_TERMINAL = (JobStatus.DONE, JobStatus.FAILED, JobStatus.CANCELLED,
             JobStatus.TIMEOUT)


class JobHandle:
    """One submitted job (created by JobService.submit)."""

    def __init__(self, job_id: int, factory: Callable, *,
                 priority: int = 0, deadline: Optional[float] = None,
                 client: str = "", name: str = "", service=None):
        self.job_id = job_id
        self.name = name or f"job{job_id}"
        self.client = client
        self.priority = int(priority)
        #: wall-clock budget in seconds, measured from submission
        self.deadline = deadline
        self.factory = factory
        self.submitted_at = time.time()
        #: monotonic twin of submitted_at — deadline expiry and queue
        #: aging must not move with NTP steps of the wall clock
        self.submitted_mono = time.monotonic()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.taskpool = None
        self._service = service
        self._status = JobStatus.PENDING
        self._result_fn: Optional[Callable[[], Any]] = None
        self._result: Any = None
        self._result_ready = False
        self._exc: Optional[BaseException] = None
        self._failed_task = None
        #: degraded-mode attribution: the peer rank whose death failed
        #: this job (None for ordinary task failures) — set by the
        #: service's containment route (PeerFailedError -> _job_error)
        self.failed_rank: Optional[int] = None
        #: the ONE terminal ``job_done`` emission happened (service
        #: seam: a recovery restart re-firing a completed pool's
        #: termination callbacks must be absorbed below the service —
        #: JobService._emit_done test-and-sets this)
        self._done_emitted = False
        # -- serving-fabric extensions (service/fabric.py); inert under
        # -- the plain JobService ---------------------------------------
        #: declared completion SLO in seconds from submission (None =
        #: best-effort); the fabric quotes a makespan at submit and
        #: queues/rejects/deprioritizes against this
        self.slo: Optional[float] = None
        #: device ask: how many exclusive accelerators to carve (0 =
        #: temporal sharing of the unreserved remainder) and the
        #: elastic ceiling the fabric may grow the subset to
        self.devices_want: int = 0
        self.devices_max: int = 0
        #: the carved memory-space subset while placed (None = shared)
        self.devices: Optional[tuple] = None
        #: preemptible round-trip state: a resumable job keeps its
        #: factory across a preemption (the pool is cancelled, the job
        #: re-queued PENDING, datarepo snapshots ride the recovery
        #: substrate) and counts how many times that happened
        self.resumable = False
        self.preemptions = 0
        self.preempted_at: Optional[float] = None
        #: admission record: the fabric's quoted makespan (seconds) and
        #: verdict ("admit" | "queue" | "deprioritize" | "reject")
        self.quote_eta: Optional[float] = None
        self.verdict: Optional[str] = None
        self._done = threading.Event()
        self._lock = threading.Lock()

    # -- state transitions (service-internal; see JobService) --------------
    def _to(self, status: JobStatus) -> bool:
        """Transition if not already terminal; returns whether it took."""
        with self._lock:
            if self._status in _TERMINAL:
                return False
            self._status = status
            if status in _TERMINAL:
                self.finished_at = time.time()
        if status in _TERMINAL:
            self._done.set()
        return True

    # -- caller API --------------------------------------------------------
    def status(self) -> JobStatus:
        return self._status

    @property
    def done(self) -> bool:
        return self._status in _TERMINAL

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the job reaches a terminal state."""
        return self._done.wait(timeout)

    def cancel(self) -> bool:
        """Cancel the job (pending: dequeue; running: cancel the pool).
        Returns True when the cancellation took, False when the job had
        already finished."""
        if self._service is None:
            return False
        return self._service.cancel(self)

    def result(self, timeout: Optional[float] = None) -> Any:
        """Block for completion and return the factory's result (the
        result_fn's return value, or None).  Raises JobCancelled /
        JobTimeout / JobError(cause) per terminal state, TimeoutError
        when ``timeout`` elapses first."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"{self.name}: not finished")
        st = self._status
        if st == JobStatus.CANCELLED:
            raise JobCancelled(f"{self.name} was cancelled")
        if st == JobStatus.TIMEOUT:
            raise JobTimeout(
                f"{self.name} exceeded its deadline ({self.deadline}s)")
        if st == JobStatus.FAILED:
            raise JobError(
                f"{self.name} failed: task {self._failed_task}"
            ) from self._exc
        with self._lock:
            if not self._result_ready:
                if self._service is not None:
                    # device tasks release deps eagerly on dispatch —
                    # pool termination means "all dispatched"; quiesce
                    # accelerators before materializing the result
                    self._service._sync_devices()
                self._result = (self._result_fn()
                                if self._result_fn is not None else None)
                self._result_ready = True
                # the closure captures the job's collections; once the
                # result is cached a resident service must not keep it
                self._result_fn = None
            return self._result

    def exception(self) -> Optional[BaseException]:
        return self._exc

    def info(self) -> Dict[str, Any]:
        """JSON-able description (server front-end / observability)."""
        return {
            "job": self.job_id,
            "name": self.name,
            "client": self.client,
            "priority": self.priority,
            "deadline": self.deadline,
            "status": self._status.name,
            "submitted_at": self.submitted_at,
            "started_at": self.started_at,
            "finished_at": self.finished_at,
            "error": (None if self._exc is None
                      else f"{type(self._exc).__name__}: {self._exc}"),
            "failed_rank": self.failed_rank,
            "slo": self.slo,
            "devices": (list(self.devices)
                        if self.devices is not None else None),
            "preemptions": self.preemptions,
            "quote_eta": self.quote_eta,
            "verdict": self.verdict,
        }

    def __repr__(self):
        return f"<JobHandle {self.name} {self._status.name}>"
