"""ServingFabric: multi-tenant mesh carving + SLO-driven admission.

The JobService (service/service.py) multiplexes concurrent jobs onto
ONE warm Context, but every job sees the whole device mesh: tenants
share every accelerator through the scheduler's load balancing, and
admission is a queue-depth check.  The serving fabric is the next
layer of the north star (PAPER.md §1, §7 — many concurrent jobs
spatially multiplexed over one warm mesh):

carving      — a free-list allocator (:class:`MeshCarver`) over the
               warm mesh's accelerator memory spaces
               (Context.accelerator_spaces) carves a DISJOINT device
               subset per exclusive tenant (best-fit contiguous runs
               first, scattered fallback).  The subset is stamped on
               the job's pool tree (``Taskpool.device_spaces``) so
               ``DeviceRegistry.best_device`` — affinity hints
               included — never leaves it.  Jobs with no device ask
               share the unreserved remainder temporally, exactly the
               old service behavior.
gang dispatch — an admitted job's whole pool tree lands on its subset
               at once (the ``_brand`` stamp covers Compound chains),
               so independent tenants run CONCURRENTLY on disjoint
               hardware instead of serially through one shared mesh.
prediction   — at submit the fabric quotes a completion makespan from
               learned per-(app, task-class) profiles through the
               calibrated dagsim model (prof/liveattr.eta_seconds),
               scaled to the subset being asked for, and verdicts the
               job against its declared SLO: ``admit``, ``queue``
               (admitted, will wait — the quote says the SLO is
               already lost), ``deprioritize`` (admitted at reduced
               priority) or ``reject`` (AdmissionError).  Profiles are
               learned from completed runs (measured makespan + live
               per-class latency profiles), closing the loop with the
               admission→completion SLO histograms.
elasticity   — when devices free up, running tenants below their
               ``devices_max`` ceiling GROW; a device death
               (:meth:`device_dead`) SHRINKS the owning tenant's
               subset in place; and a latency-critical job may PREEMPT
               a lower-priority resumable tenant mid-DAG
               (Taskpool.cancel — the collections the factory closes
               over keep their materialized tiles, the same snapshot
               substrate recovery restores from, so the resumed run
               starts from the data already produced).
audit        — every quote/admission/placement/resize/preemption/
               release decision is journaled (prof/journal.py) so
               tools/journal_audit.py can verify the fabric invariants
               offline: exclusive subsets disjoint at all times (F1),
               exactly one placement outcome per admitted job per
               admission epoch (F2), every preemption resumed or
               terminal (F3).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from parsec_tpu.service.job import (AdmissionError, JobHandle, JobStatus)
from parsec_tpu.service.service import JobService
from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose

params.register("fabric_devices_default", 0,
                "exclusive accelerators carved for a job that declares "
                "no device ask (0 = temporal sharing of the unreserved "
                "remainder, the plain JobService behavior)")
params.register("fabric_slo_policy", "queue",
                "what an over-SLO quote does at submit: 'queue' admits "
                "anyway (the verdict records the lost SLO), "
                "'deprioritize' admits at reduced priority, 'reject' "
                "raises AdmissionError; per-submit slo_policy overrides")
params.register("fabric_depri_penalty", 8,
                "priority points subtracted from an over-SLO job under "
                "the 'deprioritize' policy")
params.register("fabric_preempt_enable", 1,
                "let an SLO-carrying higher-priority job preempt a "
                "lower-priority RESUMABLE tenant when its device ask "
                "cannot be carved (0 disables preemption entirely)")
params.register("fabric_elastic", 1,
                "grow running tenants toward their devices_max ceiling "
                "when devices free up (0 freezes subsets at placement)")
params.register("fabric_profile_alpha", 0.5,
                "EWMA fold factor of the learned per-app makespan "
                "profiles the admission quote extrapolates from")
params.register("fabric_health_enable", 1,
                "consume the predictive health plane (prof/health.py): "
                "admission quotes inflate against the gang's minimum "
                "health, and a sustained below-threshold rank is "
                "pre-emptively DRAINED before the heartbeat detector "
                "declares it dead (0 ignores health entirely)")
params.register("fabric_drain_score", 0.5,
                "smoothed health score below which a rank becomes a "
                "drain candidate (the health plane's 'critical' "
                "threshold by default)")
params.register("fabric_drain_sustain_s", 3.0,
                "seconds a rank must stay below fabric_drain_score "
                "before the drain fires — one bad fold must not shed "
                "a rank")
params.register("fabric_undrain_score", 0.8,
                "smoothed score a DRAINED rank must recover to before "
                "it rejoins the placement gang")


# ---------------------------------------------------------------------------
# the free-list mesh allocator
# ---------------------------------------------------------------------------

class MeshCarver:
    """Free-list allocator over the warm mesh's accelerator memory
    spaces.  NOT self-locking: the owning fabric's service lock covers
    every mutation (carve/grow/shrink happen inside the dispatcher's
    critical section).

    Placement policy: best-fit CONTIGUOUS run first — neighboring
    space indices are neighboring devices on the mesh ring, so a
    contiguous subset keeps a tenant's ICI traffic local and leaves
    the largest holes for later tenants — with a scattered fallback
    when fragmentation leaves no run long enough (the ask still
    carves; it just spans holes)."""

    def __init__(self, spaces):
        self.spaces: Tuple[int, ...] = tuple(sorted({int(s)
                                                     for s in spaces}))
        self._free = set(self.spaces)
        self._leases: Dict[int, List[int]] = {}

    # -- introspection ----------------------------------------------------
    def free_count(self) -> int:
        return len(self._free)

    def lease(self, owner: int) -> Tuple[int, ...]:
        return tuple(self._leases.get(owner, ()))

    def leases(self) -> Dict[int, Tuple[int, ...]]:
        return {o: tuple(l) for o, l in self._leases.items()}

    def _runs(self) -> List[List[int]]:
        """Maximal runs of consecutive free space indices."""
        runs: List[List[int]] = []
        cur: List[int] = []
        for s in sorted(self._free):
            if cur and s == cur[-1] + 1:
                cur.append(s)
            else:
                if cur:
                    runs.append(cur)
                cur = [s]
        if cur:
            runs.append(cur)
        return runs

    def fragmentation(self) -> float:
        """0.0 = one contiguous hole, →1.0 = free set shattered into
        single-device holes (1 - largest_run / free)."""
        if not self._free:
            return 0.0
        return 1.0 - max(len(r) for r in self._runs()) / len(self._free)

    # -- allocation -------------------------------------------------------
    def carve(self, owner: int, n: int) -> Optional[Tuple[int, ...]]:
        """Allocate ``n`` devices for ``owner``; None when the free
        list cannot cover the ask (or the owner already holds one)."""
        if n <= 0 or owner in self._leases or n > len(self._free):
            return None
        fits = [r for r in self._runs() if len(r) >= n]
        if fits:
            take = min(fits, key=len)[:n]      # best fit: smallest run
        else:
            take = sorted(self._free)[:n]      # scattered fallback
        self._free.difference_update(take)
        self._leases[owner] = sorted(take)
        return tuple(self._leases[owner])

    def grow(self, owner: int, n: int) -> Tuple[int, ...]:
        """Add up to ``n`` free devices to an existing lease, adjacent
        spaces first; returns what was added (possibly empty)."""
        cur = self._leases.get(owner)
        if cur is None or n <= 0 or not self._free:
            return ()
        held = set(cur)
        free = sorted(self._free)
        adj = [s for s in free if s - 1 in held or s + 1 in held]
        take: List[int] = []
        for s in adj + [s for s in free if s not in adj]:
            if len(take) >= n:
                break
            if s not in take:
                take.append(s)
        self._free.difference_update(take)
        cur.extend(take)
        cur.sort()
        return tuple(take)

    def shrink(self, owner: int, n: int) -> Tuple[int, ...]:
        """Return ``n`` devices of a lease to the free list (highest
        indices first); returns what was dropped."""
        cur = self._leases.get(owner)
        if cur is None or n <= 0:
            return ()
        drop = cur[-n:]
        del cur[-len(drop):]
        self._free.update(drop)
        if not cur:
            del self._leases[owner]
        return tuple(drop)

    def release(self, owner: int) -> Tuple[int, ...]:
        cur = self._leases.pop(owner, None)
        if cur:
            self._free.update(cur)
        return tuple(cur or ())

    def evict(self, space: int) -> Optional[int]:
        """Remove a DEAD device from the pool entirely (it returns to
        no one).  Returns the owner whose lease shrank, or None when
        the device was free / unknown."""
        space = int(space)
        if space not in self.spaces:
            return None
        self.spaces = tuple(s for s in self.spaces if s != space)
        if space in self._free:
            self._free.discard(space)
            return None
        for owner, cur in self._leases.items():
            if space in cur:
                cur.remove(space)
                if not cur:
                    del self._leases[owner]
                return owner
        return None


# ---------------------------------------------------------------------------
# learned per-app profiles -> the admission quote
# ---------------------------------------------------------------------------

class FabricProfiles:
    """Per-app learned makespan profiles feeding the admission quote.

    A completed run folds (EWMA) its measured dispatch→completion
    makespan, the device count it ran on, its enumerated per-class
    task totals and the live per-class latency means (prof/liveattr).
    A quote replays those through the calibrated dagsim model
    (liveattr.eta_seconds) at the device count being ASKED for — the
    per-class means are pre-scaled so the model's implied total work
    matches the measured makespan x measured chips (eta_seconds's own
    throughput calibration assumes the quoted gang IS the measured
    one, which is exactly what a cross-subset quote must not assume).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._alpha = float(params.get("fabric_profile_alpha", 0.5))
        #: app key -> {"makespan","chips","total","means","runs"}
        self._apps: Dict[str, dict] = {}

    def observe(self, key: str, makespan: float, chips: int,
                totals: Optional[Dict[str, int]],
                means: Dict[str, float]) -> None:
        if not key or makespan <= 0.0:
            return
        chips = max(1, int(chips))
        total = sum(totals.values()) if totals else None
        with self._lock:
            p = self._apps.get(key)
            if p is None:
                self._apps[key] = {"makespan": float(makespan),
                                   "chips": chips, "total": total,
                                   "totals": dict(totals or {}),
                                   "means": dict(means), "runs": 1}
                return
            a = self._alpha
            p["makespan"] = (1 - a) * p["makespan"] + a * float(makespan)
            p["chips"] = chips
            if total is not None:
                p["total"] = total
                p["totals"] = dict(totals)
            for cls, m in means.items():
                old = p["means"].get(cls)
                p["means"][cls] = m if old is None \
                    else (1 - a) * old + a * m
            p["runs"] += 1

    def quote(self, key: str, chips: int) -> Optional[float]:
        """Predicted makespan in seconds on a ``chips``-device subset;
        None with no history for the app (first-run jobs admit on
        faith — there is nothing to quote from)."""
        with self._lock:
            p = self._apps.get(key)
            if p is None:
                return None
            makespan = p["makespan"]
            measured_chips = p["chips"]
            total = p["total"]
            totals = dict(p["totals"])
            means = dict(p["means"])
        chips = max(1, int(chips))
        if total and means:
            raw = sum(totals.get(c, 0) * m for c, m in means.items())
            f = (makespan * measured_chips / raw) if raw > 0 else 1.0
            rows = [{"cls": c, "pending": n,
                     "mean_s": means.get(c, 0.0) * f}
                    for c, n in sorted(totals.items()) if n > 0]
            try:
                from parsec_tpu.prof.liveattr import eta_seconds
                eta = eta_seconds(rows, total, chips)
                if eta is not None:
                    return eta
            except Exception:
                pass
        # no class mix on record: linear strong-scaling extrapolation
        return round(makespan * measured_chips / chips, 6)


def _job_class_stats(context, job) -> Tuple[Dict[str, float],
                                            Optional[Dict[str, int]]]:
    """(per-class latency means, enumerated per-class totals) of a
    finished job — the live-attribution rows (body profile preferred
    over the sojourn) plus liveattr.class_totals.  Best-effort: either
    side may be empty/None."""
    means: Dict[str, float] = {}
    m = getattr(context, "metrics", None)
    la = getattr(m, "_la", None) if m is not None else None
    if la is not None:
        try:
            for row in la.section().get("recs", ()):
                if row.get("job") != job.job_id:
                    continue
                prof = row.get("exec") or row.get("lat")
                if prof and prof.get("n"):
                    means[row["cls"]] = prof["sum"] / prof["n"]
        except Exception:
            means = {}
    totals = None
    try:
        from parsec_tpu.prof.liveattr import class_totals
        totals = class_totals(job.taskpool)
    except Exception:
        pass
    return means, totals


# ---------------------------------------------------------------------------
# the fabric itself
# ---------------------------------------------------------------------------

class ServingFabric(JobService):
    """JobService grown into a multi-tenant serving fabric: disjoint
    per-job device subsets, predictive SLO admission, elastic
    capacity, and a fully journaled decision trail."""

    #: class-level defaults so the dispatcher thread — started by
    #: JobService.__init__ BEFORE this subclass finishes initializing
    #: — sees a consistent (inert) fabric on its first ticks
    _carver: Optional[MeshCarver] = None
    _elastic = False
    _preempt_enable = False
    _health_enable = False
    _health_drained: frozenset = frozenset()

    def __init__(self, context=None, **kw):
        super().__init__(context, **kw)
        self._carver = MeshCarver(self.context.accelerator_spaces())
        self._profiles = FabricProfiles()
        #: chip count a SHARED (no exclusive ask) job is quoted at:
        #: the whole accelerator mesh, or the worker streams on a
        #: host-only context
        self._chips_shared = max(1, len(self._carver.spaces)
                                 or len(self.context.streams))
        self._devices_default = int(params.get("fabric_devices_default",
                                               0))
        self._slo_policy = str(params.get("fabric_slo_policy", "queue"))
        self._depri_penalty = int(params.get("fabric_depri_penalty", 8))
        self._preempt_enable = bool(int(params.get(
            "fabric_preempt_enable", 1)))
        self._elastic = bool(int(params.get("fabric_elastic", 1)))
        #: job_id -> count of STALE pool terminations to absorb: a
        #: preemption cancels the victim's pool, whose termination
        #: callback would otherwise walk the re-queued (PENDING) job
        #: into DONE through _finish (guarded-by: _lock)
        self._preempted: Dict[int, int] = {}
        self.preemptions = 0
        #: predictive health consumption (prof/health.py): ranks the
        #: fabric pre-emptively drained, and the below-threshold
        #: stopwatch feeding the sustain window (guarded-by: _lock)
        self._drain_score = float(params.get("fabric_drain_score", 0.5))
        self._drain_sustain = float(params.get("fabric_drain_sustain_s",
                                               3.0))
        self._undrain_score = float(params.get("fabric_undrain_score",
                                               0.8))
        self._health_drained = set()
        self._below_since: Dict[int, float] = {}
        self._health_next = 0.0
        self.drains = 0
        self._health_enable = bool(int(params.get(
            "fabric_health_enable", 1)))

    # -- submission: quote + verdict --------------------------------------
    def submit(self, factory, *, priority: int = 0,
               deadline: Optional[float] = None, client: str = "",
               name: str = "", block: bool = False,
               timeout: Optional[float] = None,
               slo: Optional[float] = None,
               devices: Optional[int] = None,
               devices_max: int = 0, resumable: bool = False,
               app: str = "", slo_policy: str = "") -> JobHandle:
        """Admit with a makespan quote.  ``slo`` is the declared
        completion budget in seconds from submission; ``devices`` the
        exclusive-subset ask (0/None = temporal sharing, clamped to
        the mesh); ``devices_max`` the elastic growth ceiling;
        ``resumable`` opts the job into preemption (its factory is
        kept and re-run on resume); ``app`` keys the learned profile
        (defaults to the job name)."""
        want = int(self._devices_default if devices is None else devices)
        want = max(0, min(want, len(self._carver.spaces)))
        key = app or name or getattr(factory, "__name__", "job")
        chips = want if want > 0 else self._chips_shared
        quote = self._profiles.quote(key, chips)
        # predictive admission against the health plane: a quote onto
        # a DEGRADING gang inflates by the worst live rank's smoothed
        # score, so the existing SLO policy machinery deprioritizes /
        # queues / rejects placements a degrading rank would slow —
        # before anything fails
        health = self._gang_health()
        if quote is not None and health < 1.0:
            quote = round(quote / max(health, 0.05), 6)
        policy = slo_policy or self._slo_policy
        verdict = "admit"
        eff_priority = int(priority)
        over = (slo is not None and quote is not None
                and quote > float(slo))
        if over:
            if policy == "reject":
                jid = next(self._seq)
                jr = getattr(self.context, "journal", None)
                if jr is not None:
                    jr.emit("fabric_quote", job=jid, eta=quote, app=key,
                            chips=chips, slo=float(slo), health=health)
                    jr.emit("fabric_admit", job=jid, verdict="reject",
                            eta=quote, slo=float(slo))
                raise AdmissionError(
                    f"quoted makespan {quote:.3f}s exceeds SLO "
                    f"{float(slo):g}s (policy=reject)")
            if policy == "deprioritize":
                verdict = "deprioritize"
                eff_priority -= self._depri_penalty
            else:
                verdict = "queue"
        # stamp the fabric fields UNDER the service lock: the
        # dispatcher must never pick a job whose device ask / SLO it
        # cannot see yet (the lock is reentrant; a blocking admission
        # wait fully releases it inside Condition.wait)
        with self._lock:
            job = super().submit(factory, priority=eff_priority,
                                 deadline=deadline, client=client,
                                 name=name, block=block, timeout=timeout)
            job.slo = None if slo is None else float(slo)
            job.devices_want = want
            job.devices_max = max(want, int(devices_max or 0))
            job.resumable = bool(resumable)
            job.app_key = key
            job.quote_eta = quote
            job.verdict = verdict
        jr = getattr(self.context, "journal", None)
        if jr is not None:
            jr.emit("fabric_quote", job=job.job_id, eta=quote, app=key,
                    chips=chips, slo=job.slo, health=health)
            jr.emit("fabric_admit", job=job.job_id, verdict=verdict,
                    eta=quote, slo=job.slo)
        return job

    # -- placement-aware dispatch -----------------------------------------
    def _pick_job(self, now_mono: float) -> Optional[JobHandle]:
        """Aged-priority order, but placement-aware (lock held): an
        exclusive ask dispatches only when its subset carves; a
        blocked exclusive job does NOT head-of-line-block the shared
        tenants behind it (temporal sharing of the remainder).  When
        the top ask cannot carve and preemption is armed, a
        lower-priority resumable tenant is preempted mid-DAG."""
        if self._carver is None:        # dispatcher beat __init__
            return None
        self._health_tick(now_mono)
        if self._pending and len(self._running) < self._max_active:
            order = sorted(self._pending,
                           key=lambda j: self._score(j, now_mono),
                           reverse=True)
            for job in order:
                want = int(getattr(job, "devices_want", 0) or 0)
                if want <= 0:
                    self._place(job, None)
                    return job
                lease = self._carver.carve(job.job_id, want)
                if lease is None and self._preempt_enable \
                        and job.slo is not None:
                    victim = self._pick_victim(job)
                    if victim is not None and self._preempt(victim,
                                                            job):
                        lease = self._carver.carve(job.job_id, want)
                if lease is not None:
                    self._place(job, lease)
                    return job
        self._elastic_grow()
        return None

    def _place(self, job: JobHandle, lease) -> None:
        """Record one placement outcome (lock held).  A re-placement
        after a preemption is the RESUME leg of the round-trip.  The
        ``ranks`` field stamps the gang the placement targets — live
        ranks minus the health-drained set — which is exactly what
        the auditor's H1 invariant replays: a drained rank must never
        appear in a subsequent placement's gang."""
        jr = getattr(self.context, "journal", None)
        if job.preempted_at is not None:
            job.preempted_at = None
            if jr is not None:
                jr.emit("fabric_resume", job=job.job_id)
        if lease is not None:
            job.devices = tuple(lease)
            if jr is not None:
                jr.emit("fabric_place", job=job.job_id,
                        devices=list(lease), shared=False,
                        ranks=self._gang_ranks())
        else:
            job.devices = None
            if jr is not None:
                jr.emit("fabric_place", job=job.job_id, devices=[],
                        shared=True, ranks=self._gang_ranks())

    # -- predictive health: deprioritize, then drain before death ---------
    def _gang_ranks(self) -> List[int]:
        """The placement-target gang: the context's ranks minus dead
        peers minus the health-drained set."""
        ctx = self.context
        comm = getattr(ctx, "comm", None)
        ce = getattr(comm, "ce", None) if comm is not None else None
        dead = getattr(ce, "dead_peers", None) or set()
        return [r for r in range(max(1, int(getattr(ctx, "nranks", 1))))
                if r not in dead and r not in self._health_drained]

    def _health_monitor(self):
        m = getattr(self.context, "metrics", None)
        return getattr(m, "_health", None) if m is not None else None

    def _gang_health(self) -> float:
        """Minimum smoothed health score across the live gang (1.0
        with no monitor / no observations).  Drained ranks are no
        longer placement targets, so they stop taxing quotes."""
        if not self._health_enable:
            return 1.0
        hm = self._health_monitor()
        if hm is None:
            return 1.0
        try:
            snap = hm.snapshot()
        except Exception:
            return 1.0
        vals = [e["ewma"] for r, e in snap.items()
                if r not in self._health_drained]
        return round(min(vals), 4) if vals else 1.0

    def _health_tick(self, now: float) -> None:
        """One rate-limited health consumption pass (lock held, on
        the dispatcher tick — never the task hot path): start/stop
        the below-threshold stopwatch per rank, fire the pre-emptive
        drain once the score stays below ``fabric_drain_score`` for
        ``fabric_drain_sustain_s``, lift it on sustained recovery."""
        if not self._health_enable or now < self._health_next:
            return
        self._health_next = now + 0.25
        hm = self._health_monitor()
        if hm is None:
            return
        try:
            snap = hm.refresh()
        except Exception:
            return
        my = int(getattr(self.context, "rank", 0))
        for r, ent in snap.items():
            if r == my:
                continue        # a rank cannot drain itself
            ewma = float(ent.get("ewma", 1.0))
            if r in self._health_drained:
                if ewma >= self._undrain_score:
                    self._undrain(r, ewma)
                continue
            if ewma < self._drain_score:
                since = self._below_since.setdefault(r, now)
                if now - since >= self._drain_sustain:
                    self._drain(r, ewma, hm)
            else:
                self._below_since.pop(r, None)

    # holds-lock: _lock
    def _drain(self, rank: int, ewma: float, hm) -> None:
        """Journaled pre-emptive drain: the decision carries its
        below-threshold evidence (the score series tail), the rank
        leaves the placement gang, and resumable tenants migrate off
        it through the existing preempt/resume machinery (their
        resume leg re-places onto the post-drain gang; the recovery
        plane's shrink path remains the backstop if the rank does
        die).  Fires strictly before the heartbeat detector: the
        whole point is to beat ``comm_peer_timeout_s``."""
        self._health_drained.add(rank)
        self._below_since.pop(rank, None)
        self.drains += 1
        evidence = []
        try:
            evidence = hm.evidence(rank)
        except Exception:
            pass
        jr = getattr(self.context, "journal", None)
        if jr is not None:
            jr.emit("health_drain", peer=rank, score=round(ewma, 4),
                    thr=self._drain_score,
                    sustain_s=round(self._drain_sustain, 3),
                    evidence=evidence)
        debug_verbose(1, "fabric: pre-emptive drain of rank %d "
                      "(score %.3f < %.3f sustained)", rank, ewma,
                      self._drain_score)
        self._migrate_off(rank)

    # holds-lock: _lock
    def _migrate_off(self, rank: int) -> None:
        """Migrate what can move: resumable running tenants preempt
        (cancel + re-queue with factory intact — the datarepo
        snapshot substrate keeps their materialized tiles) so their
        resume placement lands on the post-drain gang.  Non-resumable
        tenants run to completion — a drain stops NEW placement, it
        does not kill in-flight work."""
        for job in list(self._running.values()):
            if getattr(job, "resumable", False) \
                    and job.taskpool is not None \
                    and job.status() == JobStatus.RUNNING:
                self._preempt(job, job)

    # holds-lock: _lock
    def _undrain(self, rank: int, ewma: float) -> None:
        self._health_drained.discard(rank)
        jr = getattr(self.context, "journal", None)
        if jr is not None:
            jr.emit("health_undrain", peer=rank, score=round(ewma, 4))
        debug_verbose(1, "fabric: rank %d recovered (score %.3f); "
                      "drain lifted", rank, ewma)

    def _pick_victim(self, job: JobHandle) -> Optional[JobHandle]:
        """Lowest-priority RUNNING tenant that is resumable, holds an
        exclusive lease, and ranks strictly below the contender."""
        cands = [j for j in self._running.values()
                 if getattr(j, "resumable", False)
                 and j.priority < job.priority
                 and j.taskpool is not None
                 and j.status() == JobStatus.RUNNING
                 and self._carver.lease(j.job_id)]
        return min(cands, key=lambda j: (j.priority, j.job_id)) \
            if cands else None

    # holds-lock: _lock
    def _preempt(self, victim: JobHandle, by: JobHandle) -> bool:
        """Preempt a running tenant mid-DAG (lock held): cancel its
        pool (remaining tasks are discarded; the collections its
        factory closes over keep every tile already materialized —
        the datarepo snapshot substrate recovery restores from), free
        its subset, and re-queue the job PENDING with its factory
        intact for the resume leg.  False when the victim beat us to a
        terminal state (its _finish already set DONE before taking the
        lock) — nothing was touched."""
        if not victim._to(JobStatus.PENDING):   # RUNNING -> PENDING
            return False
        self._preempted[victim.job_id] = \
            self._preempted.get(victim.job_id, 0) + 1
        victim.preemptions += 1
        self.preemptions += 1
        victim.preempted_at = time.monotonic()
        self._running.pop(victim.job_id, None)
        lease = self._carver.release(victim.job_id)
        tp = victim.taskpool
        victim.taskpool = None
        victim.devices = None
        victim._result_fn = None
        self._pending.append(victim)
        jr = getattr(self.context, "journal", None)
        if jr is not None:
            jr.emit("fabric_preempt", job=victim.job_id, by=by.job_id)
            jr.emit("fabric_release", job=victim.job_id,
                    devices=list(lease), cause="preempt")
        debug_verbose(2, "fabric: preempted %s for %s (freed %s)",
                      victim.name, by.name, list(lease))
        if tp is not None:
            # safe under the reentrant lock (same precedent as the
            # deadline sweep); the stale termination is absorbed by
            # the _preempted count in _finish
            tp.cancel()
        self._work.notify_all()
        return True

    # -- branding: the carve stamp ----------------------------------------
    def _brand(self, tp, job: JobHandle) -> None:
        super()._brand(tp, job)
        tp.device_spaces = (frozenset(job.devices)
                            if job.devices else None)

    # -- completion: absorb stale terminations, free the lease ------------
    def _finish(self, job: JobHandle) -> None:
        with self._lock:
            n = self._preempted.get(job.job_id, 0)
            if n:
                if n == 1:
                    self._preempted.pop(job.job_id, None)
                else:
                    self._preempted[job.job_id] = n - 1
                absorb = True
            else:
                absorb = False
        if absorb:
            debug_verbose(2, "fabric: %s preempted; stale pool "
                          "termination absorbed", job.name)
            return
        super()._finish(job)

    def _release_job(self, job: JobHandle) -> None:
        """The job left the running set (lock held): return its subset
        to the free list, journal the release, fold the measured run
        into the app profile, and let waiting tenants grow/place."""
        if self._carver is None:
            return
        lease = self._carver.release(job.job_id)
        if lease:
            jr = getattr(self.context, "journal", None)
            if jr is not None:
                jr.emit("fabric_release", job=job.job_id,
                        devices=list(lease), cause="done")
        if job.status() == JobStatus.DONE and job.started_at \
                and job.finished_at:
            makespan = job.finished_at - job.started_at
            chips = len(lease) if lease else self._chips_shared
            means, totals = _job_class_stats(self.context, job)
            self._profiles.observe(getattr(job, "app_key", job.name),
                                   makespan, chips, totals, means)
        job.devices = None
        self._elastic_grow()
        self._work.notify_all()

    # -- elastic capacity --------------------------------------------------
    def _elastic_grow(self) -> None:
        """Grow running tenants toward their devices_max ceiling from
        the free list (lock held), highest-priority first."""
        if not self._elastic or not self._carver.free_count():
            return
        for job in sorted(self._running.values(),
                          key=lambda j: -j.priority):
            ceiling = int(getattr(job, "devices_max", 0) or 0)
            cur = self._carver.lease(job.job_id)
            if not cur or ceiling <= len(cur):
                continue
            added = self._carver.grow(job.job_id,
                                      ceiling - len(cur))
            if not added:
                continue
            job.devices = self._carver.lease(job.job_id)
            self._restamp(job)
            jr = getattr(self.context, "journal", None)
            if jr is not None:
                jr.emit("fabric_resize", job=job.job_id,
                        devices=list(job.devices), delta=len(added),
                        cause="grow")
            if not self._carver.free_count():
                return

    def device_dead(self, space: int) -> Optional[int]:
        """A device died: evict it from the mesh; the owning tenant's
        subset shrinks IN PLACE (its pool keeps running on what is
        left — the elastic counterpart of peer-death containment).
        Returns the affected job id, or None."""
        with self._lock:
            owner = self._carver.evict(space)
            self._chips_shared = max(1, len(self._carver.spaces)
                                     or len(self.context.streams))
            if owner is None:
                return None
            job = self._running.get(owner) or self._jobs.get(owner)
            if job is not None:
                job.devices = self._carver.lease(owner) or None
                self._restamp(job)
                jr = getattr(self.context, "journal", None)
                if jr is not None:
                    jr.emit("fabric_resize", job=owner,
                            devices=list(job.devices or ()), delta=-1,
                            cause="device_dead")
            return owner

    def _restamp(self, job: JobHandle) -> None:
        """Re-stamp a resized subset onto the live pool tree (plain
        attribute store; best_device reads it per dispatch)."""
        tp = job.taskpool
        if tp is None:
            return
        from parsec_tpu.core.taskpool import Compound
        stack = [tp]
        while stack:
            p = stack.pop()
            p.device_spaces = (frozenset(job.devices)
                               if job.devices else None)
            if isinstance(p, Compound):
                stack.extend(p.pools)

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        st = super().stats()
        with self._lock:
            st["fabric"] = {
                "mesh": list(self._carver.spaces),
                "free": self._carver.free_count(),
                "fragmentation": round(self._carver.fragmentation(), 4),
                "leases": {str(o): list(l) for o, l in
                           self._carver.leases().items()},
                "preemptions": self.preemptions,
                "drains": self.drains,
                "drained_ranks": sorted(self._health_drained),
                "gang_health": self._gang_health(),
            }
        return st

    def queue_position(self, job_id: int) -> Optional[int]:
        """0-based dispatch-order position of a pending job (by the
        dispatcher's aged-priority score), None when not pending."""
        with self._lock:
            now = time.monotonic()
            order = sorted(self._pending,
                           key=lambda j: self._score(j, now),
                           reverse=True)
            for i, j in enumerate(order):
                if j.job_id == job_id:
                    return i
        return None
