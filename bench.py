#!/usr/bin/env python
"""Headline benchmark: tiled GEMM GFLOPS through the runtime.

The metric of the reference's DTD GEMM perf harness (reference:
tests/dsl/dtd/dtd_test_simple_gemm.c:659-666 — GFLOPS = 2*M*N*K / wall
time over the full insert+wait cycle, i.e. the runtime's scheduling and
staging overheads count against it, not just the matmul).

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
denominator is the north-star target from BASELINE.json — 55% of the
chip's peak matmul throughput (bf16 peak for TPU platforms).
"""

import json
import os
import sys
import time
from typing import Tuple

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# Rough peak matmul GFLOP/s per chip by platform (bf16 for TPU).
_PEAKS = {
    "axon": 197_000.0,   # TPU v5e (v5 lite)
    "tpu": 197_000.0,
    "cpu": 100.0,
}


def _tile_generator(M, rand_scale: float = 0.0):
    """Jitted device-side tile generator: gen(seed, diag) -> one (mb, nb)
    tile in M's storage dtype.  Deterministic in (seed, diag), so bench
    numerics checks can REGENERATE the pre-factorization operand tiles
    instead of keeping a second resident copy of A."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def gen(seed, diag):
        shape = (M.mb, M.nb)
        # iota tiles are cheap but GLOBALLY low-rank (columns are affine
        # in the column index + per-tile constants) — fine for GEMM
        # throughput, fatal for factorizations whose later panels then
        # hit singular Schur complements.  ``rand_scale`` switches to
        # device-side Gaussian tiles; ``bump_all`` adds identity to every
        # tile (keeps stacked-panel Gram matrices well-conditioned for
        # Cholesky-QR); ``spd_diag`` makes diagonal tiles dominant so
        # Cholesky stays well-posed.
        if rand_scale > 0.0:
            key = jax.random.PRNGKey(jnp.asarray(seed, jnp.int32))
            out = rand_scale * jax.random.normal(key, shape, jnp.float32)
        else:
            x = jax.lax.broadcasted_iota(jnp.float32, shape, 1)
            out = (x * 1e-5 + seed * 1e-3) % 1.0
        out = out + diag * jnp.eye(M.mb, M.nb, dtype=jnp.float32)
        return out.astype(M.dtype) if np.dtype(M.dtype) != np.float32 \
            else out

    return gen


def prestage(M, ctx, spd_diag: bool = False, keep=None,
             bump_all: float = 0.0, rand_scale: float = 0.0) -> None:
    """Materialize every local tile directly in device HBM with a
    device-side generator (iota pattern, distinct buffer per tile) and
    attach the copies as coherent duplicates of the host tiles.

    On real hardware the host fills HBM at PCIe/DMA rates and staging is
    noise; through the axon tunnel H2D runs at a few MB/s, so staging
    GB-scale operands would time the tunnel, not the runtime.  Device-
    side init removes that artifact while keeping one distinct HBM
    buffer per logical tile (honest memory traffic for the GEMM).
    """
    import jax
    devs = ctx.device_registry.accelerators
    if not devs:
        return
    dev = devs[0]
    gen = _tile_generator(M, rand_scale)
    for i, (m, n) in enumerate(M.local_tiles()):
        if keep is not None and not keep(m, n):
            continue
        datum = M.data_of(m, n)
        diag = float(M.lm) if (spd_diag and m == n) else bump_all
        arr = jax.device_put(gen(float(i), diag), dev.jdev)
        # the generated device value becomes the newest authoritative
        # copy (the write transition lives in Data, not here)
        datum.overwrite_on(dev.space, arr)


def _discard_device_tiles(*Ms) -> None:
    """Invalidate device-resident authoritative copies WITHOUT writeback:
    bench data is synthetic, and the context-exit flush would otherwise
    D2H the whole matrix through the tunnel (minutes of pure teardown).
    """
    from parsec_tpu.data.data import Coherency
    for M in Ms:
        for t in M.local_tiles():
            d = M.data_of(*t) if isinstance(t, tuple) else M.data_of(t)
            with d._lock:
                for sp, c in list(d.copies().items()):
                    if sp != 0 and c.payload is not None:
                        d.detach_copy(sp)
                        c.payload = None
                        c.coherency = Coherency.INVALID


def _discard_device_scratch(ctx) -> None:
    """Drop device copies of NEW-flow arena temporaries (QR Q panels,
    potrf W inverses) without writeback: bench temporaries are garbage
    after the fence, and fini's flush would otherwise D2H gigabytes of
    them through the tunnel (the reason r3 never got a geqrf number
    recorded: teardown outlived the driver).  Delegates to the device's
    accounted path (XlaDevice.discard_scratch)."""
    for dev in ctx.device_registry.accelerators:
        dev.discard_scratch()



def _drain_fuse_warm(ctx, warm_again) -> None:
    """Between warmup and the timed reps: wait out the background
    fused-width compiles and run extra warm passes so the reps run
    FULLY FUSED (the r5 background warmer otherwise leaves early reps
    dispatching de-fused singles while widths compile — measured: potrf
    reps collapsed to half rate in a cold process)."""
    if not ctx.device_registry.accelerators:
        return
    from parsec_tpu.devices.xla import wait_fuse_warm
    t0 = time.perf_counter()
    ok = True
    for _ in range(2):
        ok = wait_fuse_warm() and ok
        warm_again()          # newly-ready widths' jit calls cache too
    ok = wait_fuse_warm() and ok
    log(f"fuse-width warm passes: +{time.perf_counter() - t0:.1f}s")
    if not ok:
        log("WARNING: fused-width compiles still pending after the "
            "warm window — timed reps may dispatch de-fused singles "
            "and under-read")


_CSUM = {}



def _fence(C) -> float:
    """Execution fence + dedup guard: an on-device checksum of every
    written C tile, fetched to host.  Context.wait already ends in
    ``block_until_ready`` on the last dispatched outputs, which measures
    honestly on fresh work over the axon tunnel (verified: wait time
    scales with compute) — but identical repeated computations can be
    deduped server-side, so each rep ALSO fences with a D2H readback and
    the rep's wall time is trusted only when that fence returns within
    the idle-RTT noise bound (see the rep loops); otherwise the fence
    time is folded into the timed region (ADVICE r2 medium)."""
    import jax
    import jax.numpy as jnp
    outs = []
    for m, n in C.local_tiles():
        d = C.data_of(m, n)
        v = d.newest_version()
        for _sp, c in d.copies().items():
            if c.version == v and c.payload is not None \
                    and not isinstance(c.payload, np.ndarray):
                outs.append(c.payload)
                break
    if not outs:
        return 0.0
    f = _CSUM.get(len(outs))
    if f is None:
        f = _CSUM[len(outs)] = jax.jit(
            lambda *xs: sum(jnp.sum(x) for x in xs))
    return float(np.asarray(f(*outs)))


def _fence_rtt(M) -> float:
    """Idle fence round-trip: the checksum fence timed when the device
    has no outstanding work.  The per-rep noise bound everything above
    idle-RTT is charged against."""
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        _fence(M)
        best = min(best, time.perf_counter() - t0)
    return best


def _honest_dt(dt: float, fence_dt: float, rtt0: float,
               floor: float = 0.0) -> Tuple[float, bool]:
    """The rep's accountable wall time: ``dt`` when the post-wait fence
    returned within noise of the idle RTT (wait()'s device sync covered
    completion) AND the rep is physically plausible (>= the time the
    chip's peak rate needs for the useful flops), else ``dt + fence_dt``
    (the sync under-reported; the fence observed the real completion)."""
    if fence_dt > 2.0 * rtt0 + 0.05 or dt < floor:
        if dt + fence_dt < floor:
            # even fence-inclusive the rep is physically impossible
            # (server-side dedup slipped through): it must not publish
            return -1.0, False
        return dt + fence_dt, False
    return dt, True


_PERT = {}

#: rep-r dedup bump applied by _perturb and regenerated by the potrf
#: numerics checks (bench.run_potrf_bench make_orig) — ONE definition so
#: the checks always diff against the exact perturbed operand
_PERT_SCALE = 1e-3


def _pert_value(r: int) -> float:
    return _PERT_SCALE * (r + 1)


def _perturb(M, r: int) -> None:
    """Distinct inputs per rep: bump the first local tile of ``M`` by a
    rep-dependent scalar (on device when resident).  Identical repeated
    computations can be deduped/cached server-side over the tunnel —
    a deduped rep would pass both wait() and the fence within noise and
    publish an impossible number; perturbation makes every rep fresh
    work, which is what the honest-fence methodology is calibrated for."""
    try:
        first = next(iter(M.local_tiles()))
    except StopIteration:
        log("WARNING: _perturb no-op (no local tiles) — dedup-proofing "
            "disabled for this rep")
        return
    d = M.data_of(*first)
    v = d.newest_version()
    for sp, c in list(d.copies().items()):
        p = c.payload
        if c.version == v and p is not None \
                and not isinstance(p, np.ndarray):
            import jax
            import jax.numpy as jnp
            f = _PERT.get("f")
            if f is None:
                f = _PERT["f"] = jax.jit(
                    lambda x, s: x + s.astype(x.dtype))
            d.overwrite_on(sp, f(p, jnp.float32(_pert_value(r))))
            return
    c = d.pull_to_host()
    if c is not None and c.payload is not None:
        arr = np.asarray(c.payload).copy()
        arr.flat[0] += _pert_value(r)
        d.overwrite_host(arr)
    else:
        log("WARNING: _perturb no-op (no materialized copy) — "
            "dedup-proofing disabled for this rep")


def run_gemm_bench(mb: int, mt: int, nt: int, kt: int, reps: int = 3,
                   ab_dtype=np.float32, peak_gflops: float = 0.0):
    from parsec_tpu.apps.gemm import gemm_taskpool, total_flops
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic

    rng = np.random.default_rng(7)
    # mixed precision, TPU-idiomatic: bf16 A/B panels feed the MXU at
    # full rate; C stays f32 so the k-chain accumulates in f32
    # (preferred_element_type=C.dtype in the tile kernel)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=kt * mb, name="A",
                          dtype=ab_dtype)
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=kt * mb, ln=nt * mb, name="B",
                          dtype=ab_dtype)
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=nt * mb, name="C")
    flops = total_flops(mt * mb, nt * mb, kt * mb)
    best = 0.0
    with Context(nb_cores=4) as ctx:
        on_acc = bool(ctx.device_registry.accelerators)
        if on_acc:
            # tiles are born in HBM (see prestage); host copies stay
            # zero — the timed path never reads them
            for M in (A, B, C):
                prestage(M, ctx)
        else:
            block = rng.standard_normal((mb, mb)).astype(np.float32)
            for M in (A, B, C):
                blk = block.astype(M.dtype)
                for m, n in M.local_tiles():
                    M.data_of(m, n).copy_on(0).payload[:] = blk
        # warmup: jit-compiles the tile kernel (first TPU compile 20-40s).
        # Per-rep accounting: Context.wait's device sync ends in
        # block_until_ready on the last outputs — honest on fresh work —
        # and each rep's post-wait checksum fence must return within the
        # idle-RTT noise bound or its time is charged to the rep
        # (insert+wait contract of dtd_test_simple_gemm.c:659-666).
        t0 = time.perf_counter()
        ctx.add_taskpool(gemm_taskpool(A, B, C))
        ctx.wait()
        _fence(C)
        log(f"warmup (incl. compile): {time.perf_counter() - t0:.2f}s")
        _drain_fuse_warm(ctx, lambda: (ctx.add_taskpool(
            gemm_taskpool(A, B, C)), ctx.wait(), _fence(C)))
        rtt0 = _fence_rtt(C)
        log(f"idle fence RTT: {rtt0 * 1e3:.0f} ms")
        floor = flops / (peak_gflops * 1e9) if peak_gflops else 0.0
        for r in range(reps):
            _perturb(A, r)   # fresh work every rep: dedup-proof
            t0 = time.perf_counter()
            ctx.add_taskpool(gemm_taskpool(A, B, C))
            ctx.wait()
            dt = time.perf_counter() - t0
            fs = _fence(C)
            fence_dt = time.perf_counter() - t0 - dt
            dt, in_noise = _honest_dt(dt, fence_dt, rtt0, floor)
            if dt < 0:
                log(f"rep {r}: DISCARDED (physically implausible even "
                    f"fence-inclusive — dedup suspected)")
                continue
            gf = flops / dt / 1e9
            best = max(best, gf)
            log(f"rep {r}: {dt * 1e3:.1f} ms -> {gf:.1f} GFLOP/s "
                f"(post-fence +{fence_dt * 1e3:.0f} ms"
                f"{'' if in_noise else ' COUNTED'}, csum={fs:.3e})")
        for d in ctx.device_registry.accelerators:
            if d.stats.executed_tasks:
                log(f"{d.name}: {d.stats.as_dict()}")
        _discard_device_tiles(A, B, C)
        _discard_device_scratch(ctx)
    return best


def run_potrf_bench(mb: int, nt: int, reps: int = 3,
                    peak_gflops: float = 0.0, mp: bool = False):
    """North-star metric: tiled Cholesky (BASELINE.json names DPLASMA
    dpotrf as the headline; contract like dtd_test_simple_gemm — wall
    time over insert+wait, n^3/3 useful flops).

    ``mp``: bf16-STORAGE mixed precision (HPL-AI-style) — every tile is
    stored bf16; products accumulate in f32 and the Cholesky itself runs
    in f32 (upcast around the factor kernel), but results round to bf16
    between steps.  Halves HBM footprint/traffic so larger tile grids
    fit on chip, at ~3-digit tile storage precision.  The kernels are
    dtype-following (apps/potrf.py), so this is purely a
    storage-precision choice."""
    from parsec_tpu.apps.potrf import potrf_flops, potrf_taskpool
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic

    n = nt * mb
    # mp: bf16 TILE STORAGE throughout (the collection dtype — a mixed
    # f32 diagonal would make every panel writeback a dtype-converting
    # D2H pull instead of staying device-resident); the factorization
    # itself upcasts to f32 around the Cholesky and accumulates products
    # in f32 (apps/potrf.py dtype-following kernels)
    dtype = __import__("ml_dtypes").bfloat16 if mp else np.float32
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="A", dtype=dtype)
    flops = potrf_flops(n)
    best = 0.0
    rep_gfs = []           # per-rep rates: median + band reporting
    bwd_err = None
    ir_hist = None
    # "last" (default): exact backward error once, after the final rep
    # — the O(n^3) untimed check between reps measurably depresses the
    # following rep (allocator/fragmentation churn); "all": per rep
    errcheck = os.environ.get("PARSEC_BENCH_ERRCHECK", "last")
    if errcheck == "1":
        errcheck = "last"
    with Context(nb_cores=4) as ctx:
        on_acc = bool(ctx.device_registry.accelerators)

        def reset():
            if on_acc:
                # dpotrf_L touches only the lower triangle: don't burn
                # HBM and generation work on the upper tiles
                prestage(A, ctx, spd_diag=True, keep=lambda m, n: m >= n)
            else:
                rng = np.random.default_rng(7)
                for m, nn in A.local_tiles():
                    t = rng.standard_normal((mb, mb)).astype(np.float32)
                    if m == nn:
                        t += n * np.eye(mb, dtype=np.float32)
                    arr = np.asarray(
                        A.data_of(m, nn).pull_to_host().payload)
                    arr[:] = t

        # ONE jitted generator + tile index for every rep's regeneration
        # (a fresh jax.jit closure per rep would recompile each time)
        _gen = _tile_generator(A)
        _tidx = {t: i for i, t in enumerate(A.local_tiles())}
        _first = next(iter(A.local_tiles()))

        def make_orig(r):
            """Regenerator of THIS rep's pre-factorization tiles: the
            prestage generator plus _perturb's rep bump on the first
            local tile — what the numerics checks diff LL^T against."""
            import jax.numpy as jnp

            def orig(m, nn):
                diag = float(A.lm) if m == nn else 0.0
                t = _gen(float(_tidx[(m, nn)]), diag)
                if (m, nn) == _first:
                    t = t + jnp.float32(_pert_value(r)).astype(t.dtype)
                return t
            return orig

        reset()
        t0 = time.perf_counter()
        ctx.add_taskpool(potrf_taskpool(A, device="tpu"))
        ctx.wait()
        _fence(A)
        log(f"warmup (incl. compile): {time.perf_counter() - t0:.2f}s")
        _drain_fuse_warm(ctx, lambda: (
            _discard_device_scratch(ctx), reset(), ctx.add_taskpool(
                potrf_taskpool(A, device="tpu")), ctx.wait(), _fence(A)))
        rtt0 = _fence_rtt(A)
        log(f"idle fence RTT: {rtt0 * 1e3:.0f} ms")
        floor = flops / (peak_gflops * 1e9) if peak_gflops else 0.0
        for r in range(reps):
            # drop the previous rep's dead arena scratch (panel
            # inverses) BEFORE the timed region: accumulated dead
            # buffers churn the device allocator and were measured
            # degrading later reps 96 -> 69 TF/s within one run —
            # which a median protocol is directly sensitive to
            _discard_device_scratch(ctx)
            reset()
            _perturb(A, r)   # reset() regenerates IDENTICAL data: make
            t0 = time.perf_counter()   # each rep fresh work (dedup-proof)
            ctx.add_taskpool(potrf_taskpool(A, device="tpu"))
            ctx.wait()
            dt = time.perf_counter() - t0
            fs = _fence(A)
            fence_dt = time.perf_counter() - t0 - dt
            dt, in_noise = _honest_dt(dt, fence_dt, rtt0, floor)
            if dt < 0:
                log(f"rep {r}: DISCARDED (physically implausible even "
                    f"fence-inclusive — dedup suspected)")
                continue
            gf = flops / dt / 1e9
            best = max(best, gf)
            rep_gfs.append(gf)
            extra = ""
            if on_acc and errcheck == "all":
                # untimed: exact ||A - LL^T||_F/||A||_F at bench scale
                # (VERDICT r3 #3 — the mp claim needs its error bound)
                from parsec_tpu.apps.potrf_check import backward_error
                bwd_err = backward_error(A, make_orig(r))
                extra = f", ||A-LL'||/||A||={bwd_err:.3e}"
            log(f"rep {r}: {dt * 1e3:.1f} ms -> {gf:.1f} GFLOP/s "
                f"(post-fence +{fence_dt * 1e3:.0f} ms"
                f"{'' if in_noise else ' COUNTED'}, csum={fs:.3e}{extra})")
        if errcheck == "last" and on_acc and reps:
            # after the loop: A holds the FINAL rep's factor whether or
            # not that rep's wall time published, so the error bound
            # always ships with the metric
            from parsec_tpu.apps.potrf_check import backward_error
            bwd_err = backward_error(A, make_orig(reps - 1))
            log(f"backward error ||A-LL'||/||A|| = {bwd_err:.3e}")
        if errcheck in ("all", "last") and on_acc and reps:
            # HPL-AI-style justification of low-precision storage: the
            # factor preconditions an f32 refinement solve to f32-class
            # accuracy in a few O(n^2) steps
            from parsec_tpu.apps.potrf_check import refine_solve
            ir_hist = refine_solve(A, make_orig(reps - 1), steps=3)
            log("IR solve residuals (direct, then +1 refinement step "
                f"each): {['%.3e' % h for h in ir_hist]}")
        for d in ctx.device_registry.accelerators:
            if d.stats.executed_tasks:
                log(f"{d.name}: {d.stats.as_dict()}")
        _discard_device_tiles(A)
        _discard_device_scratch(ctx)
    return best, bwd_err, ir_hist, rep_gfs


# ---------------------------------------------------------------------------
# §6 metric-table modes (SURVEY.md §6; reference harnesses:
# tests/apps/pingpong/rtt.jdf, bandwidth.jdf, tests/apps/stencil/,
# tests/profiling-standalone/sp-perf.c).  The reference publishes no
# numbers (BASELINE.md), so vs_baseline for these secondary probes is
# measured against the self-declared targets in BENCH.md.
# ---------------------------------------------------------------------------

def _pp_worker(ctx, rank, nranks, nbytes, hops):
    from parsec_tpu.apps.pingpong import run_pingpong
    trace_dir = os.environ.get("PARSEC_BENCH_TRACE_DIR")
    mod = tr = prof = None
    run_pingpong(ctx, nbytes, 8)          # warm the link + code paths
    if trace_dir:
        # install AFTER the warmup: the embedded attribution must
        # describe the measured run, not the warmup pool + gap
        from parsec_tpu.prof.causal import install_causal_tracer
        from parsec_tpu.prof.pins import install_task_profiler
        from parsec_tpu.prof.profiling import Profile
        prof = Profile(f"bench-pp-r{rank}")
        mod = install_task_profiler(ctx, prof)
        tr = install_causal_tracer(ctx, prof)
        la = getattr(ctx.metrics, "liveattr", None) \
            if ctx.metrics is not None else None
        if la is not None:
            la.reset()   # the online window = the measured run
    before = ctx.comm.stats()
    res = run_pingpong(ctx, nbytes, hops)
    after = ctx.comm.stats()
    delta = {k: after[k] - before[k] for k, v in after.items()
             if isinstance(v, (int, float)) and not isinstance(v, bool)
             and isinstance(before.get(k), (int, float))}
    delta["transport"] = after.get("transport")
    # which native paths were live on this rank (the r11 A/B record):
    # a 1 here with zero frames_parsed_native movement is a no-op
    # native path — exactly what the premerge pairing exists to catch
    delta["sched_native"] = 1 if ctx.scheduler.name == "native" else 0
    if trace_dir:
        mod.uninstall(ctx)
        tr.uninstall(ctx)
        prof.dump(os.path.join(trace_dir, f"rank{rank}.ptt"))
        la = getattr(ctx.metrics, "liveattr", None) \
            if ctx.metrics is not None else None
        if la is not None:
            # the ONLINE attribution section rides home next to the
            # trace so run_rtt_bench can embed online-vs-offline
            # agreement in the JSON line (numeric-filtered out of the
            # protocol aggregation)
            delta["liveattr_section"] = la.section()
    return res[0], res[1], delta


def _trace_attribution(trace_dir) -> dict:
    """Merge the per-rank bench traces and fold the critical-path
    attribution into the bench JSON line (informational: bench_guard
    skips it — the buckets reshuffle with host load, and the tracer
    overhead gate lives in premerge_bench.sh)."""
    import glob as _glob
    from parsec_tpu.prof import critpath
    paths = sorted(_glob.glob(os.path.join(trace_dir, "rank*.ptt")))
    att = critpath.attribution(paths)
    return {"makespan_s": round(att["makespan"], 6),
            "coverage": att["coverage"],
            "flows": att["flows"],
            **{k: round(v, 6) for k, v in att["buckets"].items()}}


def _protocol_breakdown(res) -> dict:
    """Aggregate the per-rank comm stats deltas of a pingpong run into
    the JSON protocol breakdown bench_guard watches: frames + syscalls
    per MB moved, and the eager/rdv/inline activation mix."""
    agg: dict = {}
    for _hop, _mbps, delta in res:
        for k, v in delta.items():
            if isinstance(v, (int, float)):
                agg[k] = agg.get(k, 0) + v
    mb = max(agg.get("bytes_sent", 0) + agg.get("bytes_recv", 0), 1) / 1e6
    out = {
        "transport": res[0][2].get("transport"),
        "sched_native": 1 if agg.get("sched_native") else 0,
        "frames_parsed_native": int(agg.get("frames_parsed_native", 0)),
        "frames_sent": int(agg.get("frames_sent", 0)),
        "act_eager": int(agg.get("act_eager", 0)),
        "act_rdv": int(agg.get("act_rdv", 0)),
        "act_inline": int(agg.get("act_inline", 0)),
        "coalesced_msgs": int(agg.get("coalesced_msgs", 0)),
        "wakeups": int(agg.get("wakeups", 0)),
        "partial_writes": int(agg.get("partial_writes", 0)),
        "syscalls_per_mb": round(
            (agg.get("syscalls_send", 0) + agg.get("syscalls_recv", 0))
            / mb, 3),
    }
    return out


def run_rtt_bench(hops: int = 400):
    """2-rank task round-trip latency over loopback (rtt.jdf analog):
    seconds per dataflow hop, reported in microseconds.

    ``PARSEC_BENCH_TRACE=1`` additionally traces both ranks, merges the
    traces, and embeds the critical-path attribution (exec/queue/comm/
    idle buckets, prof/critpath.py) in the JSON line — the per-hop time
    breakdown PR 3 reconstructed by hand, now tool-produced."""
    from parsec_tpu.comm.launch import run_distributed
    extras = {}
    trace_dir = None
    traced_env = {}
    if os.environ.get("PARSEC_BENCH_TRACE", "0") == "1":
        import tempfile
        trace_dir = tempfile.mkdtemp(prefix="bench-rtt-trace-")
        os.environ["PARSEC_BENCH_TRACE_DIR"] = trace_dir
        # the traced leg also arms the full online split (stride 1 +
        # the queue-wait/exec hooks) so the embedded liveattr section
        # is comparable bucket-for-bucket with the offline dict — an
        # opt-in diagnostic leg, like the tracer itself
        for k in ("PARSEC_MCA_METRICS_SAMPLE",
                  "PARSEC_MCA_METRICS_QUEUE_WAIT"):
            traced_env[k] = os.environ.get(k)
            os.environ[k] = "1"
    try:
        res = run_distributed(_pp_worker, 2, args=(8, hops), timeout=300)
    finally:
        os.environ.pop("PARSEC_BENCH_TRACE_DIR", None)
        for k, v in traced_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    value = float(np.mean([r[0] for r in res])) * 1e6
    if trace_dir:
        import shutil
        try:
            extras["attribution"] = _trace_attribution(trace_dir)
        except Exception as exc:   # the headline must still publish
            log(f"rtt trace attribution FAILED: {exc!r}")
        finally:
            shutil.rmtree(trace_dir, ignore_errors=True)
        try:
            extras.update(_online_attribution(
                res, extras.get("attribution")))
        except Exception as exc:
            log(f"rtt online attribution FAILED: {exc!r}")
    return value, {"protocol": _protocol_breakdown(res),
                   "host": _host_info(), **extras}


def _online_attribution(res, offline) -> dict:
    """Fold the per-rank liveattr sections into the ONLINE split and —
    when the offline dict landed — the per-bucket agreement in
    percentage points (informational: bench_guard skips both; the
    ISSUE acceptance bound of 10pp/bucket is enforced by
    tests/test_liveattr.py on the same leg)."""
    from parsec_tpu.prof import liveattr as la_mod
    sections = {i: r[2].get("liveattr_section")
                for i, r in enumerate(res)
                if r[2].get("liveattr_section")}
    if not sections:
        return {}
    merged = la_mod.merge_sections(sections)
    ex, qu = la_mod._bucket_sums(list(merged["recs"].values()))
    online = la_mod.telescope(merged["window_s"], ex, qu,
                              merged["comm_s"])
    out = {"attribution_online": online}
    ms = (offline or {}).get("makespan_s") or 0.0
    if ms and online["elapsed"]:
        out["attribution_agreement_pp"] = {
            b: round(abs(offline.get(b, 0.0) / ms
                         - online[b] / online["elapsed"]) * 100, 1)
            for b in ("exec", "queue", "comm", "idle")}
    return out


def run_bw_bench(nbytes: int = 8 << 20, hops: int = 32):
    """2-rank dataflow edge bandwidth (bandwidth.jdf analog), MB/s.

    The eager/rendezvous switchover is a transport-tuning knob (MPI
    implementations tune it per interconnect); on loopback the extra
    GET round-trips of rendezvous cost ~30% at this payload size, so
    the bench declares eager coverage for its own message size — the
    same choice bandwidth.jdf runs make via MCA."""
    from parsec_tpu.comm.launch import run_distributed
    prior = os.environ.get("PARSEC_MCA_comm_eager_limit")
    prior_ad = os.environ.get("PARSEC_MCA_comm_adaptive_eager")
    prior_ring = os.environ.get("PARSEC_MCA_COMM_SHM_RING_MB")
    os.environ.setdefault("PARSEC_MCA_comm_eager_limit",
                          str(nbytes * 2))
    # the probe PINS its protocol: adaptation would let a loaded host
    # demote hops to rendezvous mid-run and flip what is being measured
    os.environ.setdefault("PARSEC_MCA_comm_adaptive_eager", "0")
    # shm: size the ring for the probe's payload class (4x message —
    # measured r11: 8MB ring 379, 16MB 538, 32MB 708 MB/s at 8MB
    # payloads; a ring the producer can stream a whole frame into
    # without interleaving the consumer's parse wins).  The same MCA
    # tuning the eager pin above is; no-op on the TCP transports.
    os.environ.setdefault("PARSEC_MCA_COMM_SHM_RING_MB",
                          str(max(8, (nbytes * 4) >> 20)))
    try:
        res = run_distributed(_pp_worker, 2, args=(nbytes, hops),
                              timeout=300)
    finally:
        for key, val in (("PARSEC_MCA_comm_eager_limit", prior),
                         ("PARSEC_MCA_comm_adaptive_eager", prior_ad),
                         ("PARSEC_MCA_COMM_SHM_RING_MB", prior_ring)):
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
    value = float(np.mean([r[1] for r in res]))
    return value, {"protocol": _protocol_breakdown(res),
                   "host": _host_info()}


def _host_info() -> dict:
    """Host core inventory for the bw/rtt JSON lines (the BENCH.md r6
    'evloop frees a core' claim is only testable where cores >= 2, so
    every datapoint records where it was measured)."""
    try:
        avail = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        avail = os.cpu_count() or 1
    return {"cpu_count": os.cpu_count() or 1, "cores_available": avail}


def _empty_pool(n):
    from parsec_tpu.dsl.ptg.api import PTG, Range
    p = PTG("empty", N=n)
    p.task("E", i=Range(0, n - 1)).flow("x", "CTL").body(lambda: None)
    return p.build()


def _tasks_budget(ctx, total_us: float, k: int = 4000):
    """Staged per-task budget breakdown (µs/task), so a future tasks/s
    regression localizes to a stage instead of showing up as one opaque
    headline drop:

      construction  task-object build (C build_range or Task.__init__)
      termdet       one LOCKED counter move — the cost the per-worker
                    batching amortizes away (termdet_batch)
      dispatch      one complete_exec PINS fan-out (metrics et al.)
      progress      everything else: end-to-end per-task budget minus
                    the measured construction share (scheduling +
                    prepare/execute/complete chain, incl. the termdet
                    and dispatch shares above)

    Micro-measured in-process on the bench context; informational
    (bench_guard skips ``budget``)."""
    from parsec_tpu.core.task import Task, TaskClass
    from parsec_tpu.core.taskpool import ParameterizedTaskpool
    from parsec_tpu.core.termdet import LocalTermdet
    tp = ParameterizedTaskpool("budget-probe")
    tc = tp.add_task_class(TaskClass(
        "Bgt", params=[("i", lambda g, l: range(k))],
        body=lambda es, task: None))
    vt = tc.native_vt()
    t0 = time.perf_counter()
    if vt is not None:
        tasks = vt.build_range("i", 0, k, 1)
    else:
        tasks = [Task(tc, tp, {"i": j}) for j in range(k)]
    construction = (time.perf_counter() - t0) / k * 1e6
    td = LocalTermdet()
    td.monitor(tp, lambda: None)   # NOT_READY: counters move, no fire
    t0 = time.perf_counter()
    for _ in range(k):
        td.taskpool_addto_nb_tasks(tp, 1)
        td.taskpool_addto_nb_tasks(tp, -1)
    termdet = (time.perf_counter() - t0) / (2 * k) * 1e6
    td.unmonitor(tp)
    cbs = ctx._pins.get("complete_exec") or []
    es = ctx.streams[0]
    task = tasks[0]
    # advance the stream's retired count per iteration (restored
    # after): the metrics handler samples on nb_tasks_done % stride,
    # and a FROZEN count makes the probe bimodal — all-sampled when
    # the bench happened to end on a stride point, all-unsampled
    # otherwise.  Walking it measures the production-amortized cost.
    saved_nb = es.nb_tasks_done
    t0 = time.perf_counter()
    for _ in range(k):
        for cb in cbs:
            cb(es, "complete_exec", task)
        es.nb_tasks_done += 1
    dispatch = (time.perf_counter() - t0) / k * 1e6
    es.nb_tasks_done = saved_nb
    return {"construction_us": round(construction, 3),
            "termdet_us": round(termdet, 3),
            "dispatch_us": round(dispatch, 3),
            "progress_us": round(max(0.0, total_us - construction), 3)}


def _bail_snapshot():
    """Current per-reason fast-path bailout counters ({} when the C
    extension is absent) — benches report the DELTA across their timed
    window so a coverage regression (tasks silently popping back to
    Python) shows in the JSON next to the throughput it cost."""
    try:
        from parsec_tpu.native import load_schedext
        se = load_schedext()
        if se is not None and hasattr(se, "bailout_stats"):
            return dict(se.bailout_stats())
    except Exception:
        pass
    return {}


def _bail_delta(before, after):
    return {k: after[k] - before.get(k, 0) for k in after
            if after[k] - before.get(k, 0)}


def run_tasks_bench(n: int = 20000):
    """Empty-body task throughput, tasks/s — the DAG-scheduling
    efficiency proxy (insert+wait over n no-op tasks; every runtime
    layer except the body is on the clock).

    ``PARSEC_BENCH_TRACE=1`` runs the same probe with the FULL tracing
    stack installed (binary task profiler + causal tracer: queue-wait
    spans, dep edges) — the premerge tracer-overhead gate compares this
    against the default untraced run (tools/premerge_bench.sh)."""
    from parsec_tpu.core.context import Context
    trace = os.environ.get("PARSEC_BENCH_TRACE", "0") == "1"
    with Context(nb_cores=int(os.environ.get("PARSEC_BENCH_CORES", 4))) \
            as ctx:
        mod = tr = None
        if trace:
            from parsec_tpu.prof.causal import install_causal_tracer
            from parsec_tpu.prof.pins import install_task_profiler
            from parsec_tpu.prof.profiling import Profile
            prof = Profile("bench-tasks")
            mod = install_task_profiler(ctx, prof)
            tr = install_causal_tracer(ctx, prof)
        ctx.add_taskpool(_empty_pool(n // 10))   # warm
        ctx.wait()
        bail0 = _bail_snapshot()
        t0 = time.perf_counter()
        ctx.add_taskpool(_empty_pool(n))
        ctx.wait()
        dt = time.perf_counter() - t0
        bailouts = _bail_delta(bail0, _bail_snapshot())
        budget = _tasks_budget(ctx, dt / n * 1e6)
        if mod is not None:
            mod.uninstall(ctx)
            tr.uninstall(ctx)
        native = {"sched_native":
                  1 if ctx.scheduler.name == "native" else 0}
        doorbell = {"suppressed": ctx._db_suppressed}
    return n / dt, {"native": native, "budget": budget,
                    "doorbell": doorbell, "bailouts": bailouts}


def _chain_pool(nc: int, nb: int):
    """``nc`` independent RW data chains of length ``nb`` — the
    NON-trivial throughput workload: every task carries a real data
    flow (FromDesc binding at k==0, FromTask + local ToTask delivery
    walk inside each chain), so the whole prepare/release/complete
    machinery is on the clock, not just pop+hook."""
    from parsec_tpu.dsl.ptg import DATA, IN, OUT, PTG, Range, TASK
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    A = VectorTwoDimCyclic(1, nc).from_array(np.zeros(nc, np.float32))
    g = PTG("chains", NC=nc, NB=nb)
    g.task("S", c=Range(0, nc - 1), k=Range(0, nb - 1)) \
        .affinity(lambda c, k: A(c)) \
        .flow("T", "RW",
              IN(DATA(lambda c, k: A(c)), when=lambda c, k: k == 0),
              IN(TASK("S", "T", lambda c, k: dict(c=c, k=k - 1)),
                 when=lambda c, k: k > 0),
              OUT(TASK("S", "T", lambda c, k: dict(c=c, k=k + 1)),
                  when=lambda c, k, NB=nb: k < nb - 1)) \
        .body(lambda T, c, k: T.__iadd__(1.0) and None)
    return g.build(), A


def run_ntasks_bench(n: int = 12000):
    """NON-trivial task throughput, tasks/s: independent RW chains
    where every task binds real data and releases a local successor —
    the workload the r17 extended C progress chain (per-class binding
    tables + C-side delivery walk) exists for.  The trivial probe
    (``tasks``) bounds pure scheduling; this probe bounds the full
    dataflow path.  ``bailouts`` in the JSON must stay empty on the
    native path — any non-zero reason means tasks fell back to Python
    and the number no longer measures the C chain."""
    from parsec_tpu.core.context import Context
    nb = int(os.environ.get("PARSEC_BENCH_CHAIN_LEN", 24))
    nc = max(1, n // nb)
    n = nc * nb
    with Context(nb_cores=int(os.environ.get("PARSEC_BENCH_CORES", 4))) \
            as ctx:
        wp, _ = _chain_pool(max(1, nc // 10), nb)   # warm
        ctx.add_taskpool(wp)
        ctx.wait()
        tp, A = _chain_pool(nc, nb)
        bail0 = _bail_snapshot()
        t0 = time.perf_counter()
        ctx.add_taskpool(tp)
        ctx.wait()
        dt = time.perf_counter() - t0
        bailouts = _bail_delta(bail0, _bail_snapshot())
        native = {"sched_native":
                  1 if ctx.scheduler.name == "native" else 0}
        # every chain ran end to end: the throughput number is only
        # valid if the dataflow actually happened
        vals = np.asarray(A(0).resolve().copy_on(0).payload)
        if not np.allclose(vals, float(nb)):
            raise RuntimeError(
                f"ntasks bench: chain results wrong (want {nb}, got "
                f"{vals[:4]}...) — throughput number is invalid")
    return n / dt, {"native": native, "bailouts": bailouts,
                    "chains": {"nc": nc, "nb": nb}, "host": _host_info()}


def _agg_worker(ctx, rank: int, nranks: int, n: int):
    """Per-rank body of the aggregate probe: the trivial headline
    workload with a live RemoteDepEngine attached — every task has
    zero remote successors, so r17 comm-attached fast-complete must
    keep them ALL on the C chain (bailouts delta reports whether it
    did)."""
    ctx.add_taskpool(_empty_pool(max(200, n // 10)))   # warm
    ctx.wait(timeout=120)
    bail0 = _bail_snapshot()
    t0 = time.perf_counter()
    ctx.add_taskpool(_empty_pool(n))
    ctx.wait(timeout=300)
    dt = time.perf_counter() - t0
    return (n / dt, dt, _bail_delta(bail0, _bail_snapshot()),
            1 if ctx.scheduler.name == "native" else 0)


def run_aggregate_bench(n: int = 12000):
    """Multi-rank AGGREGATE task throughput over shm, tasks/s — the
    first whole-host scheduling-capacity number: N same-host ranks
    (self-scaled to the core count, floor 2 so the 1-core CI container
    still exercises the comm-attached path) each run the trivial
    workload with comm attached; the headline is the sum of per-rank
    rates, with per-rank scaling efficiency vs a solo comm-attached
    rank riding along.  On an oversubscribed host efficiency measures
    time-slicing fairness, not speedup — the JSON records the core
    inventory so readers can tell."""
    from parsec_tpu.comm.launch import run_distributed
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1
    nranks = int(os.environ.get("PARSEC_BENCH_AGG_RANKS",
                                max(2, min(cores, 8))))
    nb_cores = max(1, cores // nranks)
    prior = os.environ.get("PARSEC_MCA_COMM_TRANSPORT")
    os.environ["PARSEC_MCA_COMM_TRANSPORT"] = "shm"
    try:
        solo = run_distributed(_agg_worker, 1, args=(n,),
                               nb_cores=nb_cores, timeout=600)
        res = run_distributed(_agg_worker, nranks, args=(n,),
                              nb_cores=nb_cores, timeout=600)
    finally:
        if prior is None:
            os.environ.pop("PARSEC_MCA_COMM_TRANSPORT", None)
        else:
            os.environ["PARSEC_MCA_COMM_TRANSPORT"] = prior
    rates = [r[0] for r in res]
    # multi-core-only leg: the true scaling curve needs >= 1 core per
    # rank; on a smaller host the probe still runs as an N=2 smoke
    # (the comm-attached C-chain coverage is what it checks there) and
    # the JSON says WHY the scaling number is not a scaling number
    skipped = {}
    if cores < nranks:
        skipped["full_scale"] = (
            f"{cores} core(s) < {nranks} ranks: N=2 smoke only — "
            "ranks time-slice, scaling_efficiency measures fairness, "
            "not speedup")
    # aggregate over the SLOWEST rank's wall time, not a sum of rates:
    # on an oversubscribed host the ranks' windows differ wildly and a
    # rate sum double-counts the slices — this is the number a user
    # sending nranks*n tasks at the host actually experiences
    aggregate = nranks * n / max(r[1] for r in res)
    solo_rate = solo[0][0]
    eff = (aggregate / nranks / solo_rate) if solo_rate else 0.0
    bailouts: dict = {}
    for r in res:
        for k, v in r[2].items():
            bailouts[k] = bailouts.get(k, 0) + v
    return aggregate, {
        "ranks": nranks,
        "nb_cores_per_rank": nb_cores,
        "per_rank_tasks_s": [round(r, 1) for r in rates],
        "solo_tasks_s": round(solo_rate, 1),
        "scaling_efficiency": round(eff, 4),
        "native": {"sched_native": res[0][3]},
        "bailouts": bailouts,
        "host": _host_info(),
        **({"skipped": skipped} if skipped else {}),
    }


def _overhead_probe(knobs, label: str, n: int = 20000):
    """Shared armed-vs-off overhead harness (the telemetry AND journal
    gates): interleaved back-to-back pairs of the null-task probe with
    every knob in ``knobs`` set to 1 (armed) vs 0 (off).

    The reported value is the MINIMUM pair ratio — the clock
    estimator's min-RTT principle applied to an overhead gate:
    host-load noise on a shared CI core spans ~10% run to run (an
    order above the effect measured) and contaminates individual
    pairs in either direction, but a REAL regression shows in every
    pair, so the cleanest pair bounds the true overhead from below
    while staying immune to one loaded window faking a gate failure.
    The ABSOLUTE armed cost in us/task rides along: the gate that
    stays meaningful as the base gets faster (at the r14 ~1us/task
    headline a constant 0.5us plane reads as +50% ratio — the ratio
    stops measuring the code under test)."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.utils.mca import params as _params

    def rate(armed: int) -> float:
        for k in knobs:
            _params.set(k, armed)
        try:
            with Context(nb_cores=int(os.environ.get(
                    "PARSEC_BENCH_CORES", 4))) as ctx:
                ctx.add_taskpool(_empty_pool(n // 10))   # warm
                ctx.wait()
                t0 = time.perf_counter()
                ctx.add_taskpool(_empty_pool(n))
                ctx.wait()
                return n / (time.perf_counter() - t0)
        finally:
            for k in knobs:
                _params.unset(k)

    pairs = []
    us_pairs = []
    off = on = 0.0
    for _ in range(4):
        o, a = rate(0), rate(1)
        off, on = max(off, o), max(on, a)
        if a and o:
            pairs.append(max(0.0, o / a - 1.0))
            us_pairs.append(max(0.0, (1.0 / a - 1.0 / o) * 1e6))
    overhead = min(pairs) if pairs else 1.0
    overhead_us = min(us_pairs) if us_pairs else 10.0
    log(f"{label} overhead: {overhead:+.1%} / {overhead_us:.3f} "
        f"us/task (min of {['%+.1f%%' % (p * 100) for p in pairs]}; "
        f"best off {off:.0f} -> armed {on:.0f} tasks/s)")
    return overhead, {"tasks_off": round(off, 1),
                      "tasks_on": round(on, 1),
                      "overhead_us": round(overhead_us, 3)}


def run_telemetry_bench(n: int = 20000):
    """Always-on telemetry overhead, as a ratio: the tasks probe with
    the metrics registry AND flight recorder armed vs both off — the
    premerge telemetry gate's measurement (bound <= 5%, an order
    cheaper than the causal tracer's 50% gate).  The armed leg
    carries the WHOLE plane: registry + flight recorder + the live
    attribution engine with straggler detection (liveattr rides the
    metrics sampling stride, so arming it is the production
    configuration this gate bounds)."""
    return _overhead_probe(("metrics_enabled", "flightrec_enabled",
                            "liveattr_enable"), "telemetry", n)


def run_journal_bench(n: int = 20000):
    """Control-plane journal overhead on the tasks probe, armed vs
    off — the telemetry-gate discipline (interleaved pairs, min-of-
    pairs, both the ratio and the ABSOLUTE us/task cost reported).
    The journal has NO per-task emit sites by construction (every
    emit is control-plane code: recovery rounds, retirement
    handshakes, barriers, job lifecycle), so the C run_quantum fast
    path never crosses it — this gate PROVES that instead of
    asserting it in prose."""
    return _overhead_probe(("journal_enabled",), "journal", n)


def run_stencil_bench(mb: int = 0, nt: int = 8, steps: int = 0):
    """Sustained 1D 3-point stencil throughput through the runtime,
    points/s (testing_stencil_1D analog).  The probe fills HOST tiles,
    so tile size trades per-launch latency against H2D staging cost;
    override via PARSEC_BENCH_MB.

    ``PARSEC_BENCH_STENCIL_FUSE`` (default 16): sweeps fused per task
    (the S-deep-halo trade, apps/stencil.py) — per-point runtime
    overhead drops by the fusion depth at 3x the element updates, the
    winning trade for this overhead-bound fine-grained pipeline."""
    if not mb:
        mb = int(os.environ.get("PARSEC_BENCH_MB", 1 << 20))
    fuse = int(os.environ.get("PARSEC_BENCH_STENCIL_FUSE", 16))
    if not steps:
        steps = int(os.environ.get("PARSEC_BENCH_STEPS", 64))
    from parsec_tpu.apps.stencil import stencil_taskpool
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    V = VectorTwoDimCyclic(mb=mb, lm=mb * nt)
    rng = np.random.default_rng(5)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = \
            rng.standard_normal(mb).astype(np.float32)
    log(f"stencil config: mb={mb} nt={nt} steps={steps} fuse={fuse}")
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(stencil_taskpool(V, steps, fuse=fuse))
        ctx.wait()                         # warm: stage-in + compiles
        _fence(V)
        _drain_fuse_warm(ctx, lambda: (ctx.add_taskpool(
            stencil_taskpool(V, steps, fuse=fuse)), ctx.wait(),
            _fence(V)))
        rtt0 = _fence_rtt(V)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            ctx.add_taskpool(stencil_taskpool(V, steps, fuse=fuse))
            ctx.wait()
            dt = time.perf_counter() - t0
            _fence(V)
            dt, _ = _honest_dt(dt, time.perf_counter() - t0 - dt, rtt0)
            if dt > 0:
                best = max(best, mb * nt * steps / dt)
    return best


def run_tracer_bench(n: int = 100000):
    """Binary-tracer overhead per traced task, microseconds — the
    sp-perf.c analog done the way sp-perf does it: the reference's
    harness times the PROFILING LAYER in a tight single-threaded loop
    (tests/profiling-standalone/sp-perf.c), not a whole runtime run, so
    the number measures the tracer instead of scheduler noise.  Here the
    loop drives the REAL instrumentation path end to end — es.pins
    dispatch -> task_profiler callbacks -> interval bookkeeping -> the C
    trace sink — over real Task objects, and reports the marginal cost
    of the tracer being installed (dispatch with no subscribers is the
    baseline, as in the runtime's untraced hot path).

    (The r4 form — whole-runtime wall-clock with/without the tracer at
    nb_cores=4 on this 1-core host — subtracted two ~100 ms runs with a
    1.3-1.4x run-to-run spread to resolve a ~1 us effect; its 5 us
    reading was measurement noise + GIL-contention amplification, not
    tracer cost.  Microbenched pieces: raw sink append 0.14 us/event,
    full callback path ~1.3 us/task.)"""
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.task import Task
    from parsec_tpu.prof.pins import install_task_profiler
    from parsec_tpu.prof.profiling import Profile

    with Context(nb_cores=1) as ctx:
        tp = _empty_pool(4)
        ctx.add_taskpool(tp)
        ctx.wait()
        tc = tp.task_classes["E"]
        es = ctx.streams[0]
        tasks = [Task(tc, tp, {"i": k}) for k in range(n)]

        def loop():
            t0 = time.perf_counter()
            for t in tasks:
                es.pins("exec_begin", t)
                es.pins("exec_end", t)
                es.pins("complete_exec", t)
            return time.perf_counter() - t0

        loop()                                   # warm
        base = min(loop() for _ in range(3))
        mod = install_task_profiler(ctx, Profile())
        try:
            loop()                               # warm caches/JIT paths
            traced = min(loop() for _ in range(3))
        finally:
            mod.uninstall(ctx)
    return max(0.0, (traced - base) / n * 1e6)


def run_recovery_bench():
    """Recovery A/B (r13, DTD leg r15): one no-fault baseline per DAG
    (same injected body delays, no kill) plus the acceptance kill under
    MINIMAL replay and forced replay-from-restore-point
    (tools/chaos.run_ab_pair / run_ab_pair_dtd).  Value = the PTG
    killed-minimal makespan over its no-fault makespan — the metric of
    the ≤2x acceptance bound — and the extras record BOTH legs' full
    re-execution counts and makespan ratios: the
    tasks_reexecuted(minimal) < tasks_reexecuted(full) delta is the
    minimal-replay headline on each DAG (PTG recorded-lineage plan;
    DTD insert-stream skip agreement)."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import chaos
    from parsec_tpu.comm.launch import run_distributed

    def _baseline(plan: str, workload, nranks: int) -> float:
        keys = ("PARSEC_MCA_FAULT_PLAN", "PARSEC_CHAOS_WAIT_S",
                "PARSEC_MCA_RECOVERY_ENABLE")
        saved = {k: os.environ.get(k) for k in keys}
        # baseline: the SAME chain DAG under the same injected body
        # delays, no kill — the ratio isolates the RECOVERY cost
        os.environ["PARSEC_MCA_FAULT_PLAN"] = \
            "seed=11;" + plan.split(";", 2)[2]
        os.environ["PARSEC_CHAOS_WAIT_S"] = "45"
        os.environ["PARSEC_MCA_RECOVERY_ENABLE"] = "1"
        try:
            t0 = time.perf_counter()
            run_distributed(workload, nranks, timeout=90)
            return time.perf_counter() - t0
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    base_s = _baseline(chaos._ab_plan(),
                       chaos.ab_chain_recover_workload, 2)
    ab = chaos.run_ab_pair(timeout=120.0)
    ratio = ab["minimal"]["makespan_s"] / max(base_s, 1e-9)
    dtd_base_s = _baseline(chaos._dtd_ab_plan(),
                           chaos.dtd_ab_chain_workload, 3)
    dab = chaos.run_ab_pair_dtd(timeout=120.0)
    extras = {"recovery": {
        "baseline_s": round(base_s, 2),
        "minimal": ab["minimal"],
        "full": ab["full"],
        "makespan_ratio_minimal": round(ratio, 3),
        "makespan_ratio_full": round(
            ab["full"]["makespan_s"] / max(base_s, 1e-9), 3),
        "dtd": {
            "baseline_s": round(dtd_base_s, 2),
            "minimal": dab["minimal"],
            "full": dab["full"],
            "makespan_ratio_minimal": round(
                dab["minimal"]["makespan_s"] / max(dtd_base_s, 1e-9),
                3),
            "makespan_ratio_full": round(
                dab["full"]["makespan_s"] / max(dtd_base_s, 1e-9), 3),
        },
    }}
    return ratio, extras


def run_fabric_bench(n_jobs: int = 0):
    """Many-small-jobs serving throughput through the ServingFabric
    (service/fabric.py): one warm mesh, a stream of independent small
    chain jobs, jobs/s as the value and the p50/p99
    admission->completion latency in the extras — the serving-shape
    metric of the multi-tenant fabric (ISSUE 16).  The run is
    journal-audited: any F1/F2/F3 fabric-invariant violation fails the
    probe rather than reporting a number a broken fabric produced."""
    if not n_jobs:
        n_jobs = int(os.environ.get("PARSEC_BENCH_FABRIC_JOBS", 48))
    nt = int(os.environ.get("PARSEC_BENCH_FABRIC_NT", 8))
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    from parsec_tpu.service.fabric import ServingFabric

    def chain_factory(i):
        def factory():
            A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
            A.data_of(0, 0).copy_on(0).payload[:] = 0.0
            p = PTG(f"fj{i}", NT=nt)
            p.task("S", k=Range(0, nt - 1)) \
                .affinity(lambda k, A=A: A(0, 0)) \
                .flow("T", "RW",
                      IN(DATA(lambda A=A: A(0, 0)),
                         when=lambda k: k == 0),
                      IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                         when=lambda k: k > 0),
                      OUT(TASK("S", "T",
                               lambda k, NT=nt: dict(k=k + 1)),
                          when=lambda k, NT=nt: k < NT - 1),
                      OUT(DATA(lambda A=A: A(0, 0)),
                          when=lambda k, NT=nt: k == NT - 1)) \
                .body(lambda T: T + 1.0)
            return p.build()
        return factory

    log(f"fabric config: jobs={n_jobs} nt={nt}")
    with ServingFabric(nb_cores=4, max_active=8,
                       max_pending=n_jobs + 8) as svc:
        warm = [svc.submit(chain_factory(-1 - i), app="fabwarm")
                for i in range(4)]
        for j in warm:
            j.wait(timeout=60.0)
        t0 = time.perf_counter()
        jobs = [svc.submit(chain_factory(i), app="fabbench")
                for i in range(n_jobs)]
        for j in jobs:
            if not j.wait(timeout=120.0):
                raise RuntimeError(f"fabric bench: {j} never finished")
        dt = time.perf_counter() - t0
        lats = sorted(j.finished_at - j.submitted_at for j in jobs)
        bundle = {0: [svc.context.journal.snapshot()]}
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import journal_audit
    violations = journal_audit.audit(bundle)
    if violations:
        raise RuntimeError(
            f"fabric bench: journal audit found {len(violations)} "
            f"violation(s): {violations[:3]}")
    p50 = lats[len(lats) // 2]
    p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))]
    extras = {"fabric": {
        "jobs": n_jobs,
        "p50_latency_s": round(p50, 4),
        "p99_latency_s": round(p99, 4),
        "audit": "clean",
    }}
    return n_jobs / dt, extras


#: secondary §6 probes: mode -> (runner, metric name, unit, self-declared
#: target, "higher is better").  Targets documented in BENCH.md.
_AUX_MODES = {
    "rtt": (run_rtt_bench, "task_rtt", "us/hop", 1000.0, False),
    "bw": (run_bw_bench, "dataflow_bandwidth", "MB/s", 1000.0, True),
    "tasks": (run_tasks_bench, "task_throughput", "tasks/s", 10000.0, True),
    "ntasks": (run_ntasks_bench, "task_throughput_nontrivial", "tasks/s",
               10000.0, True),
    "aggregate": (run_aggregate_bench, "aggregate_task_throughput",
                  "tasks/s", 20000.0, True),
    "telemetry": (run_telemetry_bench, "telemetry_overhead", "ratio",
                  0.05, False),
    "journal": (run_journal_bench, "journal_overhead", "ratio",
                0.05, False),
    "stencil": (run_stencil_bench, "stencil_throughput", "points/s",
                1e8, True),
    "tracer": (run_tracer_bench, "tracer_overhead", "us/task", 1.0, False),
    "recovery": (run_recovery_bench, "recovery_makespan_ratio", "ratio",
                 2.0, False),
    "fabric": (run_fabric_bench, "fabric_jobs_per_s", "jobs/s",
               10.0, True),
}


# ---------------------------------------------------------------------------
# DAG scheduling efficiency (BASELINE.json metric "DAG scheduling
# efficiency 8→256 chips"; reference harness pattern:
# tests/dsl/dtd/dtd_test_simple_gemm.c:659-666 GFLOPS-vs-scale).
# Two legs:
#   A) MEASURED — the real runtime executes tiled potrf at 1/2/4/8
#      virtual devices (subprocess CPU meshes, same strategy as the
#      driver's dryrun); parallel efficiency = t1 / (n * tn).  On a
#      1-core host the virtual chips share the core, so this leg
#      measures how runtime overhead scales with device count, not
#      compute speedup — reported as such.
#   B) SIMULATED — the REAL potrf taskpool DAG (same TaskClass/Dep
#      structures, owner-computes 2D block-cyclic placement) driven
#      through the discrete-event list scheduler of parallel/dagsim.py
#      at 8..256 chips, with kernel durations calibrated on the real
#      chip and an alpha-beta ICI model.  This is the 8→256 curve.
# ---------------------------------------------------------------------------

def _eff_child(ndev: int) -> None:
    """Run tiled potrf through the full runtime on this process's
    ``ndev``-device mesh; print one JSON line {"ndev": n, "t": best}."""
    from parsec_tpu.apps.potrf import potrf_taskpool
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    mb = int(os.environ.get("PARSEC_EFF_MB", 48))
    nt = int(os.environ.get("PARSEC_EFF_NT", 10))
    n = mb * nt
    rng = np.random.default_rng(0)
    B = rng.standard_normal((n, n)).astype(np.float32)
    spd = (B @ B.T + n * np.eye(n)).astype(np.float32)

    def one_run():
        A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n,
                              ln=n).from_array(spd.copy())
        with Context(nb_cores=4) as ctx:
            A.distribute_devices(ctx)
            t0 = time.perf_counter()
            ctx.add_taskpool(potrf_taskpool(A, device="tpu"))
            ctx.wait(timeout=600)
            dt = time.perf_counter() - t0
        return dt, A

    one_run()                       # warm: compiles + code paths
    best = float("inf")
    A = None
    for _ in range(3):
        dt, A = one_run()
        best = min(best, dt)
    L = np.tril(A.to_array())
    err = np.abs(L @ L.T - spd).max() / np.abs(spd).max()
    assert err < 1e-3, f"eff-child potrf wrong: {err}"
    # per-class task seconds measured IN-RUN via the task profiler
    # (cpu kernels at this size are microsecond-class — synthetic chains
    # floor out against dispatch noise, but the profiled intervals
    # charge exactly what the runtime pays per task here, which is what
    # the simulator must reproduce): the parent validates the simulator
    # against this child's measured wall (VERDICT r4 #2)
    from parsec_tpu.prof.pins import install_task_profiler
    from parsec_tpu.prof.profiling import EV_END, EV_START, Profile
    prof = Profile()
    A2 = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n).from_array(spd.copy())
    with Context(nb_cores=1) as ctx:
        mod = install_task_profiler(ctx, prof)
        # cpu INCARNATION on ONE worker: synchronous bodies with no
        # thread interleaving, so the profiled exec intervals are true
        # per-task spans, their sum is bounded by the wall, and the
        # single-processor simulation is the exactly-comparable model
        # (4 workers on this 1-core host interleave and inflate spans
        # with descheduled time)
        t0 = time.perf_counter()
        ctx.add_taskpool(potrf_taskpool(A2, device="cpu"))
        ctx.wait(timeout=600)
        t_cpu = time.perf_counter() - t0
        mod.uninstall(ctx)
    keys = {ec.key: nm for nm, ec in prof._dict.items()}
    samples: dict = {}
    open_ev: dict = {}
    for sb in prof._streams.values():
        for key, flags, _tp, eid, _oid, ts, _info in sb.merged_events():
            if flags & EV_START:
                open_ev[eid] = (key, ts)
            elif flags & EV_END and eid in open_ev:
                kk, t0 = open_ev.pop(eid)
                samples.setdefault(keys[kk], []).append(ts - t0)
    # plain mean per class: per-task costs on this host are heavy-
    # tailed (staging/COW/allocator spikes spread across a minority of
    # tasks), so sum(mean*count) == measured body total by
    # construction — these samples validate the simulator's DAG
    # node/edge ACCOUNTING and scheduling model; the TPU leg below is
    # the fully independent duration-model validation
    durs = {nm: sum(v) / len(v) for nm, v in samples.items()}
    n_tasks = sum(len(v) for v in samples.values())
    sum_body = sum(sum(v) for v in samples.values())
    print(json.dumps({"ndev": ndev, "t": best, "t_cpu": t_cpu,
                      "n_tasks": n_tasks, "sum_body": sum_body,
                      "durs": {k: float(v) for k, v in durs.items()}}))


def _eff_measured(counts=(1, 2, 4, 8)):
    import re
    import subprocess
    repo = os.path.dirname(os.path.abspath(__file__))
    times = {}
    payloads = {}
    for nd in counts:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = \
            (flags + f" --xla_force_host_platform_device_count={nd}").strip()
        env["PARSEC_EFF_CHILD"] = str(nd)
        env.pop("PALLAS_AXON_POOL_IPS", None)   # don't claim the TPU tunnel
        try:
            proc = subprocess.run([sys.executable, "bench.py"], cwd=repo,
                                  env=env, capture_output=True, text=True,
                                  timeout=900)
        except subprocess.TimeoutExpired:
            log(f"eff child ndev={nd} timed out; skipping that point")
            continue
        if proc.returncode != 0:
            log(f"eff child ndev={nd} failed:\n" + proc.stderr[-2000:])
            continue
        for line in reversed(proc.stdout.splitlines()):
            try:
                d = json.loads(line)
                times[nd] = d["t"]
                payloads[nd] = d
                break
            except (ValueError, KeyError):
                continue
        log(f"eff measured: ndev={nd} t={times.get(nd, float('nan')):.3f}s")
    return times, payloads


def _calibrate_potrf_durations(mb: int, mp: bool, iters: int = 128):
    """Per-class kernel seconds on THIS process's device.

    Each class is timed as ONE jitted ``fori_loop`` chaining the kernel
    on its own output ``iters`` times: serially-dependent iterations
    cannot be deduped server-side (the axon tunnel caches identical
    computations) nor overlapped, and a single dispatch amortizes the
    tunnel round-trip, which is measured separately and subtracted."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from parsec_tpu.apps.potrf import tri_inv
    dt_store = jnp.bfloat16 if mp else jnp.float32
    rng = np.random.default_rng(0)
    t32 = jnp.asarray(rng.standard_normal((mb, mb)).astype(np.float32)
                      + mb * np.eye(mb, dtype=np.float32))
    tile = t32.astype(dt_store)
    eye = jnp.eye(mb, dtype=jnp.float32)

    def b_potrf(T, i):
        L = jnp.linalg.cholesky(T.astype(jnp.float32) + mb * eye)
        W = tri_inv(L)
        # re-symmetrize the carry so the next chol stays well-posed; the
        # W-dependent term keeps the inverse live in the loop (an extra
        # rank-0 update -- POTRF reads a hair high, the safe side)
        return (jnp.matmul(L, L.T) + W[0, 0] * 1e-9).astype(T.dtype)

    def b_trsm(C, i):
        return jnp.matmul(C, eye.astype(C.dtype).T,
                          preferred_element_type=jnp.float32
                          ).astype(C.dtype)

    def b_syrk(T, i):
        acc = jnp.matmul(T, T.T, preferred_element_type=jnp.float32)
        return (T.astype(jnp.float32) - 1e-3 * acc).astype(T.dtype)

    def b_gemm(C, i):
        acc = jnp.matmul(C, C.T, preferred_element_type=jnp.float32)
        return (C.astype(jnp.float32) - 1e-3 * acc).astype(C.dtype)

    def timed(body, x0):
        @jax.jit
        def run(x):
            return lax.fori_loop(0, iters, lambda i, c: body(c, i), x)
        from parsec_tpu.devices.xla import _transient_compile_error
        try:
            jax.block_until_ready(run(x0))  # warm/compile
        except Exception as exc:
            if not _transient_compile_error(exc):
                raise
            log("calibration: transient compile flake; retrying once")
            jax.block_until_ready(run(x0))
        rtt = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(jnp.add(jnp.float32(1), jnp.float32(1)))
            rtt = min(rtt, time.perf_counter() - t0)
        # median-of-3: the tunnel RTT jitters by tens of ms either way,
        # and best-of would systematically pick the most-understated rep
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(run(x0))
            samples.append((time.perf_counter() - t0 - rtt) / iters)
        med = sorted(samples)[1]
        if med <= 2e-7:
            log(f"calibration WARNING: kernel time floored "
                f"(samples {samples}) — raise iters")
        return max(med, 1e-7)

    durs = {
        "POTRF": timed(b_potrf, tile),
        "TRSM": timed(b_trsm, tile),
        "SYRK": timed(b_syrk, tile),
        "GEMM": timed(b_gemm, tile),
    }
    durs["POTRFL"] = durs["POTRF"] * 0.4    # no tri_inv on the last tile
    return durs


def _pq(n: int):
    p = int(np.sqrt(n))
    while n % p:
        p -= 1
    return p, n // p


def run_eff_bench():
    from parsec_tpu.apps.potrf import potrf_taskpool
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.parallel.dagsim import (build_dag, critical_path,
                                            simulate)
    import jax
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")

    # Leg A: the real runtime at 1/2/4/8 virtual devices
    times, payloads = _eff_measured()
    meas_eff = {nd: times[1] / (nd * t) for nd, t in times.items()
                if 1 in times}

    # Leg A': sim-vs-measured validation on the CPU leg (VERDICT r4 #2).
    # Each child runs one cpu-incarnation potrf (synchronous bodies)
    # with the task profiler on, reporting its wall AND the per-class
    # body times the profiler measured — the coherent (measured,
    # durations) pair.  The host's workers share ONE physical core, so
    # the comparable simulation is the same DAG on a single time-sliced
    # processor (total work + per-task overhead; the parallel model is
    # validated on the TPU leg below).  Two independent samples: the
    # nd=1 and nd=8 children.
    sim_vs_meas = {}
    mb_c = int(os.environ.get("PARSEC_EFF_MB", 48))
    nt_c = int(os.environ.get("PARSEC_EFF_NT", 10))
    for nd in (1, 8):
        d = payloads.get(nd, {}).get("durs")
        t_cpu = payloads.get(nd, {}).get("t_cpu")
        nta = payloads.get(nd, {}).get("n_tasks")
        sbod = payloads.get(nd, {}).get("sum_body")
        if not d or not t_cpu or not nta:
            continue
        # per-task runtime overhead CALIBRATED from the same run (real
        # data-carrying tasks pay staging/COW/release — ms-class on
        # this host, far above the empty-task probe): the scalar is
        # fitted, so what this sample validates is the DAG's node/edge
        # ACCOUNTING and the list-scheduling model reproducing the
        # measured makespan from per-class medians
        ovh_cpu = max(0.0, (t_cpu - sbod) / nta)
        Ac = TwoDimBlockCyclic(mb=mb_c, nb=mb_c, lm=nt_c * mb_c,
                               ln=nt_c * mb_c)
        dag_c = build_dag(potrf_taskpool(Ac, device="cpu"),
                          lambda tc, loc, D=d: D.get(tc, max(D.values())))
        pred = simulate(dag_c, 1, overhead=ovh_cpu)["makespan_s"]
        errp = 100.0 * (pred - t_cpu) / t_cpu
        sim_vs_meas[f"cpu_sample{nd}_pct"] = round(errp, 1)
        log(f"eff sim-vs-measured (cpu incarnation, child nd={nd}, "
            f"overhead {ovh_cpu * 1e6:.0f}us/task calibrated in-run): "
            f"predicted {pred:.3f}s vs measured {t_cpu:.3f}s "
            f"({errp:+.1f}%)")

    # Leg B: calibrated DAG simulation at 8..256 chips.  nt=128 at
    # mb=6144 puts ~2.3GB of bf16 tiles per chip at 256 chips — the
    # constant-memory-per-chip operating point DPLASMA-class scaling
    # runs use; smaller grids starve 256 chips on the panel critical
    # path and measure the problem size, not the scheduler
    mb = int(os.environ.get("PARSEC_EFF_SIM_MB", 6144 if on_tpu else 256))
    nt = int(os.environ.get("PARSEC_EFF_SIM_NT", 128))
    mp = os.environ.get("PARSEC_BENCH_POTRF_MP", "1") == "1"
    durs = _calibrate_potrf_durations(mb, mp)
    log(f"eff sim: calibrated kernel seconds at mb={mb} mp={mp}: "
        + ", ".join(f"{k}={v * 1e3:.2f}ms" for k, v in durs.items()))
    # per-task runtime overhead: from the measured task-throughput probe
    # class (~20us/task on the 1-core build host; a real pod host does
    # better, so this is conservative)
    ovh = float(os.environ.get("PARSEC_EFF_OVERHEAD_US", 20.0)) * 1e-6
    alpha = float(os.environ.get("PARSEC_EFF_ALPHA_US", 2.0)) * 1e-6
    beta = float(os.environ.get("PARSEC_EFF_BETA_GBS", 45.0)) * 1e9
    itemsize = 2 if mp else 4
    tile_bytes = mb * mb * itemsize
    curve = {}
    dag = None
    for nchips in (8, 16, 32, 64, 128, 256):
        P, Q = _pq(nchips)
        A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=nt * mb, ln=nt * mb,
                              nodes=nchips, P=P, Q=Q)
        tp = potrf_taskpool(A, device="cpu")
        dag = build_dag(tp, lambda tc, loc: durs[tc],
                        bytes_fn=lambda tc, fl: tile_bytes)
        res = simulate(dag, nchips, alpha=alpha, beta=beta, overhead=ovh)
        curve[nchips] = res["efficiency"]
        log(f"eff sim: {nchips:3d} chips ({P}x{Q}): "
            f"eff={res['efficiency']:.3f} makespan={res['makespan_s']:.3f}s "
            f"tasks={res['n_tasks']}")
    cp = critical_path(dag, overhead=ovh)
    log(f"eff sim: critical path {cp:.3f}s (infinite-chip bound); "
        f"per-task overhead {ovh * 1e6:.0f}us, alpha {alpha * 1e6:.0f}us, "
        f"beta {beta / 1e9:.0f}GB/s, tile {tile_bytes >> 20}MiB")

    # Leg B': sim-vs-measured on the REAL chip at potrf bench scale
    # (VERDICT r4 #2): the same calibrated durations + overhead predict
    # a single-chip makespan; one measured potrf run provides the truth.
    if on_tpu and os.environ.get("PARSEC_EFF_VALIDATE_TPU", "1") == "1":
        nt_v = int(os.environ.get("PARSEC_BENCH_NT", 16))
        gf, _be, _ir, _reps = run_potrf_bench(mb, nt_v, reps=3, mp=mp)
        n_v = mb * nt_v
        measured = (n_v ** 3 / 3.0) / (gf * 1e9)
        Av = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n_v, ln=n_v)
        dag_v = build_dag(potrf_taskpool(Av, device="cpu"),
                          lambda tc, loc: durs[tc])
        pred = simulate(dag_v, 1, overhead=ovh)["makespan_s"]
        errp = 100.0 * (pred - measured) / measured
        sim_vs_meas["tpu_1chip_pct"] = round(errp, 1)
        log(f"eff sim-vs-measured (TPU, 1 chip, mb={mb} nt={nt_v}): "
            f"predicted {pred:.3f}s vs measured {measured:.3f}s "
            f"({errp:+.1f}%)")
    return meas_eff, curve, sim_vs_meas


# ---------------------------------------------------------------------------
# flop accounting (ISSUE r6 tentpole c): per-class HIGHEST vs DEFAULT
# flops of the factorizations, the attainable rate they imply, and how
# much of it the measured number achieves — so the remaining gap is
# measured, not guessed.  Rates are calibratable constants: DEFAULT is
# the measured GEMM-class MXU rate (r5: 155 TF/s on v5e = 0.79 of bf16
# peak), HIGHEST is its measured ~3x tax; override via
# PARSEC_BENCH_RATE_DEFAULT / PARSEC_BENCH_RATE_HIGHEST (GFLOP/s).
# ---------------------------------------------------------------------------

def _accounting_rates(peak_gflops: float):
    r_lo = float(os.environ.get("PARSEC_BENCH_RATE_DEFAULT",
                                0.79 * peak_gflops))
    r_hi = float(os.environ.get("PARSEC_BENCH_RATE_HIGHEST", r_lo / 3.0))
    return max(r_hi, 1e-9), max(r_lo, 1e-9)


def _qr_flop_accounting(mb: int, nt: int, ib: int, peak_gflops: float,
                        achieved_gflops: float):
    """Analytic HIGHEST/DEFAULT flop split of the blocked tiled QR
    (apps/qr.py kernels, flat-tree): per class, per instance, times the
    instance count.  With inner blocking the HIGHEST work per panel
    task is O(mb^2*ib); unblocked (ib=0) it is O(mb^3)."""
    def geqrt_split():
        if ib:
            hi = lo = 0.0
            for s in range(0, mb, ib):
                rest = mb - s - ib
                hi += 4.0 * mb * ib * s          # re-projection pass
                hi += 8.0 * mb * ib * ib         # CholeskyQR2 (2x gram+Q)
                hi += 2.0 * ib ** 3              # chol/tri_inv/R folds
                if rest > 0:
                    lo += 4.0 * mb * ib * rest   # trailing update
            return hi, lo
        # whole-tile CholeskyQR2: 2x (gram + Q formation) + inverses
        return 10.0 * mb ** 3, 0.0

    def tsqrt_split():
        if ib:
            hi = lo = 0.0
            for s in range(0, mb, ib):
                rest = mb - s - ib
                hi += 2.0 * ib * ib * (ib + mb)  # gram of [Rjj; Bj]
                hi += 2.0 * mb * ib * ib + 3.0 * ib ** 3   # V, invs, Tt
                hi += 2.0 * ib * mb * s + 2.0 * ib * s * s  # T-accum
                if rest > 0:
                    lo += (4.0 * mb * ib + 2.0 * ib * ib) * rest  # WY
            return hi, lo
        # whole-panel gram + 2 tri_inv + WY products, all HIGHEST
        return 9.0 * mb ** 3, 0.0

    counts = {
        "GEQRT": nt,
        "UNMQR": nt * (nt - 1) // 2,
        "TSQRT": nt * (nt - 1) // 2,
        "TSMQR": sum(j * j for j in range(1, nt)),
    }
    g = geqrt_split()
    t = tsqrt_split()
    per = {"GEQRT": g, "UNMQR": (0.0, 2.0 * mb ** 3), "TSQRT": t,
           "TSMQR": (0.0, 6.0 * mb ** 3)}
    from parsec_tpu.apps.qr import geqrf_flops as _gf
    return _emit_accounting("geqrf", counts, per,
                            _gf(nt * mb, nt * mb), peak_gflops,
                            achieved_gflops, extra={"ib": ib})


def _potrf_flop_accounting(mb: int, nt: int, peak_gflops: float,
                           achieved_gflops: float):
    """Executed-flop accounting of the tiled Cholesky (apps/potrf.py):
    every class is DEFAULT-precision matmul-class work; the interesting
    ratio is executed/useful (the TRSM-by-inverse + full-SYRK tax)."""
    counts = {
        "POTRF": max(nt - 1, 0) if nt > 1 else 0,
        "POTRFL": 1,
        "TRSM": nt * (nt - 1) // 2,
        "SYRK": nt * (nt - 1) // 2,
        "GEMM": sum((nt - 1 - k) * (nt - 2 - k) // 2
                    for k in range(nt - 1)),
    }
    per = {"POTRF": (0.0, mb ** 3), "POTRFL": (0.0, mb ** 3 / 3.0),
           "TRSM": (0.0, 2.0 * mb ** 3), "SYRK": (0.0, 2.0 * mb ** 3),
           "GEMM": (0.0, 2.0 * mb ** 3)}
    from parsec_tpu.apps.potrf import potrf_flops as _pf
    return _emit_accounting("potrf", counts, per, _pf(nt * mb),
                            peak_gflops, achieved_gflops)


def _emit_accounting(name, counts, per, useful, peak_gflops, achieved,
                     extra=None):
    """Common tail: totals, attainable rate, table to stderr, JSON
    dict back to the caller."""
    r_hi, r_lo = _accounting_rates(peak_gflops)
    classes = {}
    hi_tot = lo_tot = 0.0
    for cls, cnt in counts.items():
        hi1, lo1 = per[cls]
        classes[cls] = {
            "count": cnt,
            "highest_gflop": round(hi1 * cnt / 1e9, 1),
            "default_gflop": round(lo1 * cnt / 1e9, 1),
        }
        hi_tot += hi1 * cnt
        lo_tot += lo1 * cnt
    t_attain = hi_tot / (r_hi * 1e9) + lo_tot / (r_lo * 1e9)
    attainable = useful / t_attain / 1e9 if t_attain > 0 else 0.0
    log(f"{name} flop accounting (rates: HIGHEST {r_hi / 1e3:.1f} "
        f"TF/s, DEFAULT {r_lo / 1e3:.1f} TF/s; useful "
        f"{useful / 1e12:.1f} TFLOP):")
    log(f"  {'class':8s} {'count':>6s} {'HIGHEST GF':>12s} "
        f"{'DEFAULT GF':>12s}")
    for cls, row in classes.items():
        log(f"  {cls:8s} {row['count']:6d} {row['highest_gflop']:12.1f} "
            f"{row['default_gflop']:12.1f}")
    log(f"  executed/useful = {(hi_tot + lo_tot) / max(useful, 1):.2f}, "
        f"HIGHEST share = "
        f"{hi_tot / max(hi_tot + lo_tot, 1) * 100:.1f}%, attainable "
        f"{attainable / 1e3:.1f} TF/s, achieved {achieved / 1e3:.1f} "
        f"TF/s ({achieved / max(attainable, 1e-9) * 100:.0f}% of "
        f"attainable)")
    out = {
        "classes": classes,
        "rates_gflops": {"highest": round(r_hi, 1),
                         "default": round(r_lo, 1)},
        "executed_vs_useful": round((hi_tot + lo_tot) / max(useful, 1),
                                    3),
        "highest_share": round(hi_tot / max(hi_tot + lo_tot, 1), 4),
        "attainable_gflops": round(attainable, 1),
        "achieved_vs_attainable": round(
            achieved / max(attainable, 1e-9), 4),
    }
    if extra:
        out.update(extra)
    return out


def run_geqrf_bench(mb: int, nt: int, reps: int = 3,
                    peak_gflops: float = 0.0, mp: bool = False):
    """Tiled QR (BASELINE.md names dgeqrf-class drivers alongside
    dpotrf; useful flops 2mn^2 - 2n^3/3, insert+wait contract).

    ``mp``: bf16 tile STORAGE (same HPL-AI-style discipline as the
    potrf mp mode — the WY construction and all accumulations stay
    f32, results round to bf16 between steps; halves HBM so larger
    grids fit and doubles MXU rate on the TSMQR matmuls)."""
    from parsec_tpu.apps.qr import geqrf_flops, qr_taskpool
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic

    n = nt * mb
    dtype = __import__("ml_dtypes").bfloat16 if mp else np.float32
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="A",
                          dtype=dtype)
    flops = geqrf_flops(n, n)
    best = 0.0
    # sibling-batching window: each dispatched program costs ~10-15ms of
    # tunnel-fixed overhead and the QR wavefronts release in bursts, so
    # a few ms of batching cuts the program count ~4x (xla.py
    # device_fuse_window_ms); scoped to this bench via params override
    from parsec_tpu.utils.mca import params as _params
    fw = float(os.environ.get("PARSEC_BENCH_GEQRF_FUSEWIN", "4"))
    _params.set("device_fuse_window_ms", fw)
    # inner blocking (apps/qr.py ib discipline): HIGHEST panel work
    # drops O(mb^3) -> O(mb^2*ib); PARSEC_BENCH_GEQRF_IB=0 reproduces
    # the unblocked r5 construction for A/B attribution
    ib = int(os.environ.get("PARSEC_BENCH_GEQRF_IB", 512))
    _params.set("qr_ib", ib)
    try:
        return _run_geqrf_inner(A, mb, nt, n, flops, reps, peak_gflops,
                                mp)
    finally:
        _params.unset("device_fuse_window_ms")
        _params.unset("qr_ib")


def _geqrf_orig_fn(A, last_rep: int):
    """Regenerator of the geqrf bench's pre-factorization tiles — the
    prestage generator (Gaussian 0.05 + identity bump) plus the last
    rep's dedup perturbation on the first local tile.  ONE definition
    shared by the residual check and the LS-refine ladder, so both
    always validate the exact operand that was factored."""
    import jax.numpy as jnp
    gen = _tile_generator(A, 0.05)
    tiles = list(A.local_tiles())
    first = tiles[0]
    lin_of = {t: i for i, t in enumerate(tiles)}

    def orig(m, nn):
        t = gen(float(lin_of[(m, nn)]), 1.0).astype(jnp.float32)
        if (m, nn) == first:
            t = t + jnp.float32(_pert_value(last_rep))
        return t
    return orig


def _geqrf_residual_check(A, ctx, last_rep: int) -> float:
    """Stochastic factorization check WITHOUT storing Q: an orthogonal
    QR satisfies R^T R = A^T A, so compare the two quadratic forms on a
    random probe vector (O(n^2) matvecs, tile-streamed).  R is the
    bench result sitting in A's tiles (upper block triangle; TSQRT
    zeroed the rest); the original A regenerates from the deterministic
    device-side generator plus the last rep's perturbation."""
    import jax
    import jax.numpy as jnp
    dev = ctx.device_registry.accelerators[0]
    nt_, mb_ = A.mt, A.mb
    tiles = list(A.local_tiles())
    rng = np.random.default_rng(123)
    z = [jax.device_put(rng.standard_normal(mb_).astype(np.float32),
                        dev.jdev) for _ in range(nt_)]
    orig = _geqrf_orig_fn(A, last_rep)

    def rtile(m, nn):
        d = A.data_of(m, nn)
        c = d.copies().get(dev.space) or d.pull_to_host()
        return jnp.asarray(c.payload).astype(jnp.float32)

    mv = jax.jit(lambda t, v: t @ v)
    mtv = jax.jit(lambda t, v: t.T @ v)
    w = [jnp.zeros(mb_, jnp.float32) for _ in range(nt_)]
    v = [jnp.zeros(mb_, jnp.float32) for _ in range(nt_)]
    for m, nn in tiles:
        w[m] = w[m] + mv(orig(m, nn), z[nn])
        if m <= nn:
            v[m] = v[m] + mv(rtile(m, nn), z[nn])
    y1 = [jnp.zeros(mb_, jnp.float32) for _ in range(nt_)]
    y2 = [jnp.zeros(mb_, jnp.float32) for _ in range(nt_)]
    for m, nn in tiles:
        y2[nn] = y2[nn] + mtv(orig(m, nn), w[m])
        if m <= nn:
            y1[nn] = y1[nn] + mtv(rtile(m, nn), v[m])
    num = float(jnp.sqrt(sum(jnp.sum((a - b) ** 2)
                             for a, b in zip(y1, y2))))
    den = float(jnp.sqrt(sum(jnp.sum(b ** 2) for b in y2)))
    return num / den if den else float("nan")


def _run_geqrf_inner(A, mb, nt, n, flops, reps, peak_gflops, mp):
    from parsec_tpu.apps.qr import qr_taskpool
    from parsec_tpu.core.context import Context
    best = 0.0
    with Context(nb_cores=4) as ctx:
        on_acc = bool(ctx.device_registry.accelerators)

        def reset():
            if on_acc:
                # Gaussian tiles + identity bump: the GLOBAL matrix must
                # be full-rank (iota tiles are not) and stacked-panel
                # Gram matrices well-conditioned for Cholesky-QR
                prestage(A, ctx, bump_all=1.0, rand_scale=0.05)
            else:
                rng = np.random.default_rng(7)
                for m, nn in A.local_tiles():
                    arr = np.asarray(
                        A.data_of(m, nn).pull_to_host().payload)
                    arr[:] = rng.standard_normal((mb, mb)
                                                 ).astype(np.float32)

        reset()
        t0 = time.perf_counter()
        ctx.add_taskpool(qr_taskpool(A, device="tpu"))
        ctx.wait()
        _fence(A)
        log(f"warmup (incl. compile): {time.perf_counter() - t0:.2f}s")
        _drain_fuse_warm(ctx, lambda: (
            _discard_device_scratch(ctx), reset(), ctx.add_taskpool(
                qr_taskpool(A, device="tpu")), ctx.wait(), _fence(A)))
        rtt0 = _fence_rtt(A)
        log(f"idle fence RTT: {rtt0 * 1e3:.0f} ms")
        floor = flops / (peak_gflops * 1e9) if peak_gflops else 0.0
        for r in range(reps):
            _discard_device_scratch(ctx)   # see potrf rep loop
            reset()
            _perturb(A, r)
            t0 = time.perf_counter()
            ctx.add_taskpool(qr_taskpool(A, device="tpu"))
            ctx.wait()
            dt = time.perf_counter() - t0
            fs = _fence(A)
            fence_dt = time.perf_counter() - t0 - dt
            dt, in_noise = _honest_dt(dt, fence_dt, rtt0, floor)
            if dt < 0:
                log(f"rep {r}: DISCARDED (physically implausible even "
                    f"fence-inclusive — dedup suspected)")
                continue
            gf = flops / dt / 1e9
            best = max(best, gf)
            log(f"rep {r}: {dt * 1e3:.1f} ms -> {gf:.1f} GFLOP/s "
                f"(post-fence +{fence_dt * 1e3:.0f} ms"
                f"{'' if in_noise else ' COUNTED'}, csum={fs:.3e})")
        for d in ctx.device_registry.accelerators:
            if d.stats.executed_tasks:
                log(f"{d.name}: {d.stats.as_dict()}")
        residual = None
        ladder = None
        if on_acc and reps and \
                os.environ.get("PARSEC_BENCH_ERRCHECK", "last") != "0":
            residual = _geqrf_residual_check(A, ctx, reps - 1)
            log(f"factorization residual ||R'Rz-A'Az||/||A'Az|| = "
                f"{residual:.3e}")
            # mp-QR accuracy ladder (VERDICT r5 #9, apps/qr_check.py):
            # CSNE solve with the factored R as preconditioner — the
            # HPL-AI contract for the QR driver, recorded like potrf's
            # ir_residuals.  O(n^2) per step, untimed; validates the
            # SAME regenerated operand the residual check diffed.
            from parsec_tpu.apps.qr_check import ls_refine
            steps = int(os.environ.get("PARSEC_BENCH_LS_STEPS", 4))
            ladder = ls_refine(A, _geqrf_orig_fn(A, reps - 1),
                               steps=steps)
            log("LS-refine errors (CSNE direct, then +1 refinement "
                f"step each): {['%.3e' % h for h in ladder]}")
        _discard_device_tiles(A)
        _discard_device_scratch(ctx)
    return best, residual, ladder


def main():
    child = os.environ.get("PARSEC_EFF_CHILD")
    if child:
        _eff_child(int(child))
        return
    import jax
    platform = jax.devices()[0].platform
    log(f"platform: {platform}, devices: {len(jax.devices())}")
    on_tpu = platform in ("tpu", "axon")
    app = os.environ.get("PARSEC_BENCH_APP", "gemm")
    if app == "eff":
        meas_eff, curve, sim_vs_meas = run_eff_bench()
        value = curve.get(256, 0.0)
        # self-declared target (BENCH.md): >= 0.5 parallel efficiency at
        # 256 chips on the calibrated-simulation leg
        print(json.dumps({
            "metric": "dag_scheduling_efficiency_256",
            "value": round(value, 4),
            "unit": "efficiency",
            "vs_baseline": round(value / 0.5, 4),
            "sim_curve": {str(k): round(v, 4) for k, v in curve.items()},
            "measured_virtual_mesh": {str(k): round(v, 4)
                                      for k, v in meas_eff.items()},
            "sim_vs_measured_pct": sim_vs_meas,
            "note": "sim_curve: real potrf DAG, list-scheduled, kernel "
                    "durations calibrated on this chip, alpha-beta ICI; "
                    "measured_virtual_mesh: t1/(n*tn) of the real runtime "
                    "on n virtual devices sharing this host's core(s) — "
                    "overhead scaling, not compute speedup; "
                    "sim_vs_measured_pct: predicted-vs-measured makespan "
                    "error of the SAME simulator (cpu legs on one "
                    "time-sliced core; tpu leg on the real chip)",
        }))
        return
    if app in _AUX_MODES:
        fn, metric, unit, target, higher = _AUX_MODES[app]
        value = fn()
        extras = {}
        if isinstance(value, tuple):
            value, extras = value
        # lower-is-better ratios cap at 100: a PERFECT reading (the
        # telemetry mode's 0.0 overhead is common) must score best,
        # not divide to zero and read as a collapse to artifact diffs
        vs = (value / target) if higher \
            else (min(100.0, target / value) if value else 100.0)
        print(json.dumps({
            "metric": metric,
            "value": round(value, 3),
            "unit": unit,
            "vs_baseline": round(vs, 4),
            **extras,
        }))
        return
    if app == "geqrf":
        # r5: bf16 STORAGE by default (distinct tiled_geqrf_mp metric,
        # the potrf-mp discipline) at nt=10 — TSMQR bulk dominates the
        # panel-construction cost there; the f32 contract stays one env
        # flip away.  The WY construction runs at HIGHEST precision
        # either way (DEFAULT bf16-pass matmuls DESTROY the
        # factorization, measured residual 1.19 — BENCH.md geqrf note),
        # and every bench run now records the factorization residual.
        mp = on_tpu and os.environ.get("PARSEC_BENCH_GEQRF_MP", "1") == "1"
        mb = int(os.environ.get("PARSEC_BENCH_MB", 6144 if on_tpu else 16))
        # nt=8 mp: 4.8GB resident bf16 tiles — nt=10 measured marginally
        # better when the tunnel server was healthy but OOMs under
        # server memory pressure; robustness wins for the default
        nt = int(os.environ.get("PARSEC_BENCH_NT",
                                (8 if mp else 6) if on_tpu else 3))
        from parsec_tpu.utils.mca import params as _params
        _params.set("device_fuse",
                    int(os.environ.get("PARSEC_BENCH_FUSE", 8)))
        # tighter windows than potrf: the HIGHEST-precision TSQRT
        # programs carry larger workspace and nt=10 keeps 100 tiles
        # resident — depth 32 OOMed a 16GB v5e (r5)
        _params.set("device_runahead",
                    int(os.environ.get("PARSEC_BENCH_RUNAHEAD", 20)))
        _params.set("device_inflight_depth",
                    int(os.environ.get("PARSEC_BENCH_DEPTH", 12)))
        # ONE clamp rule (qr.effective_ib) decides what the kernels run
        # AND what the log/accounting/JSON report — set the param first,
        # exactly as run_geqrf_bench will
        from parsec_tpu.apps.qr import effective_ib
        from parsec_tpu.utils.mca import params as _p
        _p.set("qr_ib", int(os.environ.get("PARSEC_BENCH_GEQRF_IB", 512)))
        try:
            ib = effective_ib(mb)
        finally:
            _p.unset("qr_ib")
        fuse_panel = os.environ.get("PARSEC_MCA_DEVICE_FUSE_PANEL", "1")
        log(f"geqrf config: mb={mb} nt={nt} mixed-precision={mp} "
            f"ib={ib} fuse_panel={fuse_panel}")
        peak = _PEAKS.get(platform, 100.0)
        value, residual, ladder = run_geqrf_bench(
            mb, nt, reps=int(os.environ.get("PARSEC_BENCH_REPS", 3)),
            peak_gflops=peak, mp=mp)
        accounting = _qr_flop_accounting(mb, nt, ib, peak, value)
        print(json.dumps({
            "metric": "tiled_geqrf_mp_gflops" if mp
                      else "tiled_geqrf_gflops",
            "value": round(value, 1),
            "unit": "GFLOP/s",
            "vs_baseline": round(value / (0.55 * peak), 4),
            "storage": "bfloat16" if mp else "float32",
            "ib": ib,
            "fuse_panel": fuse_panel not in ("0", "false"),
            **({"factorization_residual": float(f"{residual:.3e}")}
               if residual is not None else {}),
            **({"ls_refine_errors": [float(f"{h:.3e}") for h in ladder]}
               if ladder else {}),
            "flop_accounting": accounting,
        }))
        return
    if os.environ.get("PARSEC_BENCH_APP", "gemm") == "potrf":
        print(json.dumps(_potrf_headline(platform, on_tpu)))
        return
    # Big MXU-friendly tiles on TPU, small ones on CPU CI.  12288 tiles
    # carry ~3.7 TFLOP of MXU work each, amortizing the ~2.4ms/launch
    # tunnel overhead; bf16 panels run the systolic array at full rate
    # with f32 accumulation in C (sweep: mb 2048->0.6, 4096->48,
    # 8192->144, 12288->158; deepening k to 4 -> 163 TFLOP/s on v5e).
    mb = int(os.environ.get("PARSEC_BENCH_MB", 12288 if on_tpu else 64))
    mt = nt = int(os.environ.get("PARSEC_BENCH_NT", 3 if on_tpu else 4))
    kt = int(os.environ.get("PARSEC_BENCH_KT", 4))
    reps = int(os.environ.get("PARSEC_BENCH_REPS", 3))
    ab = os.environ.get("PARSEC_BENCH_AB_DTYPE", "bfloat16" if on_tpu
                        else "float32")
    peak = _PEAKS.get(platform, 100.0)
    value = run_gemm_bench(mb, mt, nt, kt, reps=reps,
                           ab_dtype=np.dtype(ab) if ab != "bfloat16"
                           else __import__("ml_dtypes").bfloat16,
                           peak_gflops=peak)
    target = 0.55 * peak
    out = {
        "metric": "tiled_gemm_gflops",
        "value": round(value, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(value / target, 4),
    }
    # driver-capture the north star (VERDICT r5 #6): the default mode
    # ALSO runs the potrf median-of-5 headline and folds it into the
    # same (single) JSON line, so the driver-recorded BENCH_r*.json
    # carries tiled_potrf_mp_gflops — the metric that gates COMPLETE —
    # every round, not only when a builder runs the potrf mode by hand.
    # PARSEC_BENCH_NORTHSTAR=0 restores the gemm-only default.
    if os.environ.get("PARSEC_BENCH_NORTHSTAR", "1") != "0":
        log("--- north-star leg: potrf median-of-5 ---")
        try:
            ns = _potrf_headline(platform, on_tpu)
            out[ns["metric"]] = ns["value"]
            for key in ("rep_band_gflops", "best_gflops", "protocol",
                        "backward_error", "ir_residuals", "storage",
                        "fuse_panel"):
                if key in ns:
                    out["potrf_" + key] = ns[key]
            out["potrf_vs_baseline"] = ns["vs_baseline"]
        except Exception as exc:     # the headline must still publish
            log(f"north-star potrf leg FAILED: {exc!r}")
            out["potrf_error"] = str(exc)[:200]
    print(json.dumps(out))


def _potrf_headline(platform, on_tpu):
    """The north-star potrf headline (median-of-5 protocol): returns
    the JSON-ready dict; the potrf mode prints it as-is and the default
    (gemm) mode folds it into its own line so the driver artifact
    always records ``tiled_potrf_mp_gflops``."""
    # r3: TRSM runs as matmul against the POTRF-emitted triangular
    # inverse (apps/potrf.py tri_inv — jsl trsm measured ~18 TF/s vs
    # matmul ~150 TF/s on v5e) and same-class waves ride fused
    # launches (devices/xla.py device_fuse), so larger tile grids now
    # pay off: the r2 sweep (4096/8 -> 33.7, 6144/8 -> 40.0 TFLOP/s)
    # was launch-latency-bound on the serialized panel chain
    # bf16-panel mixed precision by default on TPU: fits nt=16 at
    # mb=6144 in HBM, where the executed/useful flop ratio (the
    # TRSM-by-inverse + full-SYRK tax) drops to ~1.2 and compute
    # dominates the tunnel's per-launch latency
    mp = on_tpu and os.environ.get("PARSEC_BENCH_POTRF_MP", "1") == "1"
    mb = int(os.environ.get("PARSEC_BENCH_MB", 6144 if on_tpu else 32))
    # nt=16 mp: 10.3GB resident bf16 tiles + ~2.5GB fused-launch
    # transients on a 16GB v5e
    nt = int(os.environ.get("PARSEC_BENCH_NT",
                            (16 if mp else 12) if on_tpu else 4))
    from parsec_tpu.utils.mca import params as _params
    _params.set("device_fuse",
                int(os.environ.get("PARSEC_BENCH_FUSE", 8)))
    # a tight run-ahead window: eager completion would otherwise keep
    # every unfinalized output (each panel inverse, every fused-wave
    # operand set) referenced until the end of the pool — at nt=14
    # that overflows the 16GB HBM; finalizing promptly lets donation
    # and GC recycle chain buffers
    _params.set("device_runahead",
                int(os.environ.get("PARSEC_BENCH_RUNAHEAD", 48)))
    # one width-8 fused launch fills the default inflight depth of 8
    # (entries are TASKS, not launches): deepen so dispatch pipelines
    _params.set("device_inflight_depth",
                int(os.environ.get("PARSEC_BENCH_DEPTH", 32)))
    fuse_panel = os.environ.get("PARSEC_MCA_DEVICE_FUSE_PANEL", "1")
    log(f"potrf config: mb={mb} nt={nt} mixed-precision={mp} "
        f"fuse_panel={fuse_panel}")
    peak = _PEAKS.get(platform, 100.0)
    # 4 reps: the first timed rep still hits a few fresh fused-width
    # compiles; best-of converges by rep 2-3
    # median-of-5 protocol (VERDICT r4 #6): tunnel-state variance
    # spans ~20% run to run, so the RECORDED value is the median
    # with the observed band alongside — one lucky (or unlucky)
    # rep no longer moves the headline
    value_best, bwd_err, ir_hist, rep_gfs = run_potrf_bench(
        mb, nt, reps=int(os.environ.get("PARSEC_BENCH_REPS", 5)),
        peak_gflops=peak, mp=mp)
    import statistics
    value = statistics.median(rep_gfs) if rep_gfs else value_best
    # the mp (bf16-storage) variant reports under its OWN metric name
    # with the storage precision and measured backward error in the
    # JSON — not apples-to-apples with the full-precision dpotrf
    # contract (ADVICE r3 medium)
    out = {
        "metric": "tiled_potrf_mp_gflops" if mp
                  else "tiled_potrf_gflops",
        "value": round(value, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(value / (0.55 * peak), 4),
        "storage": "bfloat16" if mp else "float32",
        "fuse_panel": fuse_panel not in ("0", "false"),
    }
    if rep_gfs:
        out["rep_band_gflops"] = [round(min(rep_gfs), 1),
                                  round(max(rep_gfs), 1)]
        out["best_gflops"] = round(value_best, 1)
        out["protocol"] = "median-of-%d" % len(rep_gfs)
    if bwd_err is not None:
        out["backward_error"] = float(f"{bwd_err:.4e}")
    if ir_hist is not None:
        out["ir_residuals"] = [float(f"{h:.3e}") for h in ir_hist]
    out["flop_accounting"] = _potrf_flop_accounting(mb, nt, peak,
                                                    value)
    return out


if __name__ == "__main__":
    main()
