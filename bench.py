#!/usr/bin/env python
"""Headline benchmark: tiled GEMM GFLOPS through the runtime.

The metric of the reference's DTD GEMM perf harness (reference:
tests/dsl/dtd/dtd_test_simple_gemm.c:659-666 — GFLOPS = 2*M*N*K / wall
time over the full insert+wait cycle, i.e. the runtime's scheduling and
staging overheads count against it, not just the matmul).

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
denominator is the north-star target from BASELINE.json — 55% of the
chip's peak matmul throughput (bf16 peak for TPU platforms).
"""

import json
import os
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# Rough peak matmul GFLOP/s per chip by platform (bf16 for TPU).
_PEAKS = {
    "axon": 197_000.0,   # TPU v5e (v5 lite)
    "tpu": 197_000.0,
    "cpu": 100.0,
}


def run_gemm_bench(mb: int, mt: int, nt: int, kt: int, reps: int = 3):
    from parsec_tpu.apps.gemm import gemm_taskpool, total_flops
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic

    rng = np.random.default_rng(7)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=kt * mb, name="A")
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=kt * mb, ln=nt * mb, name="B")
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=nt * mb, name="C")
    for M in (A, B, C):
        for m, n in M.local_tiles():
            M.data_of(m, n).copy_on(0).payload[:] = \
                rng.standard_normal((mb, mb)).astype(np.float32)

    flops = total_flops(mt * mb, nt * mb, kt * mb)
    best = 0.0
    with Context(nb_cores=4) as ctx:
        # warmup: jit-compiles the tile kernel (first TPU compile 20-40s)
        t0 = time.perf_counter()
        ctx.add_taskpool(gemm_taskpool(A, B, C))
        ctx.wait()
        log(f"warmup (incl. compile): {time.perf_counter() - t0:.2f}s")
        for r in range(reps):
            t0 = time.perf_counter()
            ctx.add_taskpool(gemm_taskpool(A, B, C))
            ctx.wait()
            dt = time.perf_counter() - t0
            gf = flops / dt / 1e9
            best = max(best, gf)
            log(f"rep {r}: {dt * 1e3:.1f} ms -> {gf:.1f} GFLOP/s")
        for d in ctx.device_registry.accelerators:
            if d.stats.executed_tasks:
                log(f"{d.name}: {d.stats.as_dict()}")
    return best


def main():
    import jax
    platform = jax.devices()[0].platform
    log(f"platform: {platform}, devices: {len(jax.devices())}")
    on_tpu = platform in ("tpu", "axon")
    # 64 GEMM tasks; big MXU-friendly tiles on TPU, small ones on CPU CI
    mb = 2048 if on_tpu else 64
    mt = nt = kt = 4
    value = run_gemm_bench(mb, mt, nt, kt)
    peak = _PEAKS.get(platform, 100.0)
    target = 0.55 * peak
    print(json.dumps({
        "metric": "tiled_gemm_gflops",
        "value": round(value, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(value / target, 4),
    }))


if __name__ == "__main__":
    main()
